"""orchlint: AST invariant lint for the orchestrator's own contracts.

The reference tree leans on `go vet` and the race detector in CI; this
port's equivalents are conventions — and conventions rot. Six invariant
families are machine-checked here (stdlib `ast`, no dependencies), run
as a tier-1 test so a violation fails the build:

  determinism      inside `chaos/`, `kubemark/*_soak.py` and `sched/`,
                   wall-clock reads (`time.time()`, `datetime.now()`)
                   and unseeded process RNG (`random.random()`,
                   `random.Random()`, `np.random.*`) are banned: one
                   stray draw or wall read silently breaks the
                   `trace() == schedule()` replay contract every chaos
                   plan is built on. Time flows through
                   `utils/clock.Clock`, randomness through per-
                   `(seed, stream)` `random.Random` instances.
  lock-discipline  in `core/store.py` / `core/wal.py`, code holding the
                   ledger lock (`self._lock`) must not publish (watcher
                   sends, `_drain_publish`/`_fanout`), sleep, do HTTP,
                   or perform non-WAL blocking I/O — the two-phase
                   stage/ledger/publish split is enforced lexically.
                   Acquiring `_pub_lock` under the ledger lock is a
                   statically-detected lock-order inversion (the
                   sanctioned order is publish -> ledger, see
                   `Store._watch_register`).
  jax-hygiene      in `sched/device/`, host syncs (`.item()`,
                   `float()`/`int()` casts, `np.asarray`) and Python
                   branching on traced parameters are flagged inside
                   jitted functions and `lax.scan` bodies — each one is
                   a silent device->host round trip in the scan hot
                   path.
  shard-sync       also in `sched/device/`: outputs of jitted dispatch
                   (sharded `jax.Array`s under a mesh) pulled to host
                   INSIDE a per-tile/per-chunk Python loop —
                   `jax.device_get`, `np.asarray`/`.item()`/scalar
                   casts on them, or Python branching on a per-shard
                   value — each is a cross-shard gather + host sync
                   per tile that serializes the async dispatch
                   pipeline. Collect device references in the loop and
                   transfer once after it.
  api-idempotency  a retry loop around a bare POST (`create`/`bind`
                   without an idempotency guard) outside `api/retry.py`
                   is flagged: replaying an ambiguous POST duplicates
                   objects; retries belong in `RetryPolicy`, which
                   knows which verbs are safe.
  metric-pinning   in `kubemark/`, a registry read (`counter_sum`,
                   `summary_stats`, `histogram*`, ...) or an `SLODef`
                   whose statically-resolvable metric name is not
                   pinned in `utils/metrics.py` is flagged: a gate
                   must not be one rename away from asserting on a
                   counter nobody increments (the DURABILITY_COUNTERS
                   no-drift contract, generalized).

Pre-existing accepted sites live in `lint/baseline.toml` — explicit,
counted, and with a reason each. A new violation is a hard error; so is
baseline drift (a fixed violation whose allowance was not removed).

Run: `python -m kubernetes_tpu.lint [--json]`; the tier-1 gate is
tests/test_lint.py. The runtime complement (lock-order witness) is
`lint/lockwitness.py`.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .baseline import Baseline, load_baseline

__all__ = [
    "Violation", "LintReport", "run_lint", "lint_source", "lint_file",
    "Baseline", "load_baseline", "RULES", "DEFAULT_BASELINE",
]

#: repo-relative path of the checked-in allowlist
DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.toml")


@dataclass(frozen=True)
class Violation:
    """One rule hit. `key()` is the baseline identity: it survives
    line-number drift (edits above a site must not invalidate the
    allowlist), so it is (file, rule, enclosing def, symbol) with an
    occurrence COUNT carried by the baseline side."""

    rule: str          # rule family, e.g. "determinism"
    path: str          # repo-relative posix path
    line: int
    col: int
    site: str          # dotted enclosing scope, e.g. "Store.create"
    symbol: str        # machine tag, e.g. "time.time"
    message: str

    def key(self) -> Tuple[str, str, str, str]:
        return (self.path, self.rule, self.site, self.symbol)

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"[{self.rule}] {self.site}: {self.message}")


@dataclass
class LintReport:
    violations: List[Violation] = field(default_factory=list)
    #: violations not covered by the baseline — hard errors
    new: List[Violation] = field(default_factory=list)
    #: baseline entries whose allowance exceeds what the tree still
    #: contains — fixed violations that must be removed from the
    #: baseline (drift is an error too, or the allowlist only grows)
    stale: List[str] = field(default_factory=list)
    files_scanned: int = 0
    seconds: float = 0.0

    @property
    def ok(self) -> bool:
        return not self.new and not self.stale

    def to_dict(self) -> dict:
        return {
            "ok": self.ok,
            "files_scanned": self.files_scanned,
            "seconds": round(self.seconds, 4),
            "violations_total": len(self.violations),
            "new": [v.__dict__ for v in self.new],
            "stale_baseline": list(self.stale),
        }


# --------------------------------------------------------------- helpers

def _import_table(tree: ast.AST) -> Dict[str, str]:
    """Local name -> fully-qualified module path, from this module's
    imports — so `import time as _time; _time.time()` resolves to
    `time.time` and a variable merely NAMED `random` does not."""
    table: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                table[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom) and node.module \
                and node.level == 0:
            for a in node.names:
                if a.name == "*":
                    continue
                table[a.asname or a.name] = f"{node.module}.{a.name}"
    # conventional scientific aliases resolve even without the import
    # (fixture snippets in tests use them bare)
    table.setdefault("np", "numpy")
    table.setdefault("jnp", "jax.numpy")
    return table


def _dotted(node: ast.AST) -> Optional[str]:
    """`a.b.c` for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _resolve(node: ast.AST, imports: Dict[str, str]) -> Optional[str]:
    """Dotted name with its head rewritten through the import table."""
    dotted = _dotted(node)
    if dotted is None:
        return None
    head, _, rest = dotted.partition(".")
    full = imports.get(head)
    if full is None:
        return dotted
    return f"{full}.{rest}" if rest else full


class _ScopedVisitor(ast.NodeVisitor):
    """NodeVisitor that maintains the dotted enclosing-scope name
    (ClassDef/FunctionDef chain) so violations carry a stable site."""

    def __init__(self, path: str, imports: Dict[str, str]):
        self.path = path
        self.imports = imports
        self.scope: List[str] = []
        self.out: List[Violation] = []

    @property
    def site(self) -> str:
        return ".".join(self.scope) or "<module>"

    def _push(self, name: str, node: ast.AST) -> None:
        self.scope.append(name)
        self.generic_visit(node)
        self.scope.pop()

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._push(node.name, node)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._push(node.name, node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._push(node.name, node)

    def flag(self, rule: str, node: ast.AST, symbol: str,
             message: str) -> None:
        self.out.append(Violation(
            rule=rule, path=self.path, line=getattr(node, "lineno", 0),
            col=getattr(node, "col_offset", 0), site=self.site,
            symbol=symbol, message=message))


# ----------------------------------------------------- rule: determinism

_WALL_CLOCK = {
    "time.time": "wall-clock read",
    "datetime.datetime.now": "wall-clock read",
    "datetime.datetime.utcnow": "wall-clock read",
    "datetime.datetime.today": "wall-clock read",
    "datetime.date.today": "wall-clock read",
}


class _DeterminismVisitor(_ScopedVisitor):
    RULE = "determinism"

    def visit_Call(self, node: ast.Call) -> None:
        name = _resolve(node.func, self.imports)
        if name in _WALL_CLOCK:
            self.flag(self.RULE, node, name,
                      f"{name}() is a wall-clock read; seeded/replayed "
                      f"code must take time from utils/clock.Clock "
                      f"(monotonic() for deadlines, now() only for "
                      f"API-object timestamps)")
        elif name == "random.Random" and not node.args:
            self.flag(self.RULE, node, "random.Random()",
                      "unseeded random.Random() breaks trace()=="
                      "schedule() replay; seed it from the plan's "
                      "(seed, stream) contract")
        elif name is not None and name.startswith("random.") \
                and name != "random.Random":
            self.flag(self.RULE, node, name,
                      f"{name}() draws from the shared process RNG; "
                      f"all randomness here must come from a per-"
                      f"(seed, stream) random.Random instance")
        elif name is not None and name.startswith("numpy.random.") \
                and not (name == "numpy.random.default_rng"
                         and node.args):
            self.flag(self.RULE, node, name,
                      f"{name}() uses numpy's global (or unseeded) "
                      f"RNG; use numpy.random.default_rng(seed)")
        self.generic_visit(node)


def check_determinism(tree: ast.AST, path: str) -> List[Violation]:
    v = _DeterminismVisitor(path, _import_table(tree))
    v.visit(tree)
    return v.out


# -------------------------------------------------- rule: lock-discipline

#: attribute names of the two store locks (on self)
_LEDGER_LOCK = "self._lock"
_PUB_LOCK = "self._pub_lock"

#: blocking-I/O call heads banned under either lock (the WAL is the
#: one sanctioned writer under the ledger lock: any `self._wal*`
#: receiver or method is exempt)
_BLOCKING_HEADS = ("urllib", "http", "requests", "socket")
_BLOCKING_CALLS = {"open", "os.fsync", "os.replace", "os.unlink",
                   "os.makedirs", "json.dump", "json.load",
                   "time.sleep"}
_WATCHER_METHODS = {"send", "send_many"}
_PUBLISH_METHODS = {"self._drain_publish", "self._fanout"}


class _LockDisciplineVisitor(_ScopedVisitor):
    RULE = "lock-discipline"

    def __init__(self, path: str, imports: Dict[str, str]):
        super().__init__(path, imports)
        self.held: List[str] = []

    def visit_With(self, node: ast.With) -> None:
        acquired = []
        for item in node.items:
            name = _dotted(item.context_expr)
            if name in (_LEDGER_LOCK, _PUB_LOCK):
                if name == _PUB_LOCK and _LEDGER_LOCK in self.held:
                    self.flag(self.RULE, node, "lock-order-inversion",
                              "acquiring _pub_lock while holding the "
                              "ledger lock inverts the sanctioned "
                              "publish->ledger order "
                              "(Store._watch_register) and can "
                              "deadlock against it")
                acquired.append(name)
        self.held.extend(acquired)
        self.generic_visit(node)
        del self.held[len(self.held) - len(acquired):]

    def _is_wal_exempt(self, name: Optional[str]) -> bool:
        return name is not None and (name.startswith("self._wal")
                                     or ".__wal" in name)

    def visit_Call(self, node: ast.Call) -> None:
        if self.held:
            name = _dotted(node.func)
            resolved = _resolve(node.func, self.imports)
            ledger = _LEDGER_LOCK in self.held
            if not self._is_wal_exempt(name):
                method = (node.func.attr
                          if isinstance(node.func, ast.Attribute)
                          else None)
                head = (resolved or "").partition(".")[0]
                if ledger and name in _PUBLISH_METHODS:
                    self.flag(self.RULE, node, "publish-under-ledger-lock",
                              f"{name}() runs the publish phase while "
                              f"the ledger lock is held; publish must "
                              f"run after release (two-phase commit)")
                elif ledger and method in _WATCHER_METHODS:
                    self.flag(self.RULE, node,
                              "watcher-callback-under-ledger-lock",
                              f".{method}() is a watcher callback; "
                              f"fan-out must not run under the ledger "
                              f"lock")
                elif head in _BLOCKING_HEADS:
                    self.flag(self.RULE, node, "http-under-lock",
                              f"{resolved}() does network I/O while "
                              f"holding a store lock")
                elif resolved in _BLOCKING_CALLS:
                    self.flag(self.RULE, node, "blocking-io-under-lock",
                              f"{resolved}() is blocking I/O under a "
                              f"store lock; only the WAL may block "
                              f"the ledger")
                elif method == "sleep":
                    self.flag(self.RULE, node, "blocking-io-under-lock",
                              f"{name}() sleeps while holding a store "
                              f"lock")
        self.generic_visit(node)


def check_lock_discipline(tree: ast.AST, path: str) -> List[Violation]:
    v = _LockDisciplineVisitor(path, _import_table(tree))
    v.visit(tree)
    return v.out


# ------------------------------------------------------ rule: jax-hygiene

def _jit_decorated(node: ast.FunctionDef, imports: Dict[str, str]) -> bool:
    for dec in node.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        name = _resolve(target, imports)
        if name in ("jax.jit", "jax.pmap"):
            return True
        if name in ("functools.partial", "partial") \
                and isinstance(dec, ast.Call) and dec.args:
            inner = _resolve(dec.args[0], imports)
            if inner in ("jax.jit", "jax.pmap"):
                return True
    return False


def _scan_body_names(tree: ast.AST, imports: Dict[str, str]) -> set:
    """Names of locally-defined functions passed as the body of
    jax.lax.scan / jax.lax.fori_loop / jax.lax.while_loop — traced
    regions even without a @jit decorator."""
    names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            name = _resolve(node.func, imports)
            if name in ("jax.lax.scan", "jax.lax.fori_loop",
                        "jax.lax.while_loop", "jax.lax.map"):
                for arg in node.args:
                    if isinstance(arg, ast.Name):
                        names.add(arg.id)
    return names


class _TracedRegionVisitor(_ScopedVisitor):
    """Checks ONE traced function body (params are traced values)."""

    RULE = "jax-hygiene"

    def __init__(self, path: str, imports: Dict[str, str],
                 scope: List[str], params: set):
        super().__init__(path, imports)
        self.scope = list(scope)
        self.params = params

    def _mentions_param(self, node: ast.AST) -> bool:
        return any(isinstance(n, ast.Name) and n.id in self.params
                   for n in ast.walk(node))

    def visit_Call(self, node: ast.Call) -> None:
        resolved = _resolve(node.func, self.imports)
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr == "item":
            self.flag(self.RULE, node, "host-sync-item",
                      ".item() inside a traced region forces a "
                      "device->host sync per call")
        elif resolved in ("float", "int", "bool") and node.args \
                and not isinstance(node.args[0], ast.Constant):
            self.flag(self.RULE, node, f"host-sync-{resolved}",
                      f"{resolved}() on a traced value concretizes it "
                      f"on host; use jnp casts/astype")
        elif resolved is not None and (resolved.startswith("numpy.")):
            self.flag(self.RULE, node, resolved,
                      f"{resolved}() inside a traced region pulls the "
                      f"array to host; keep the hot path on device "
                      f"(jnp equivalents)")
        self.generic_visit(node)

    def visit_If(self, node: ast.If) -> None:
        if self._mentions_param(node.test):
            self.flag(self.RULE, node, "python-branch-on-traced",
                      "Python `if` on a traced value fails (or "
                      "silently specializes) under jit; use jnp.where "
                      "/ lax.cond")
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        if self._mentions_param(node.test):
            self.flag(self.RULE, node, "python-branch-on-traced",
                      "Python `while` on a traced value cannot trace; "
                      "use lax.while_loop")
        self.generic_visit(node)


def check_jax_hygiene(tree: ast.AST, path: str) -> List[Violation]:
    imports = _import_table(tree)
    scan_bodies = _scan_body_names(tree, imports)
    out: List[Violation] = []

    def walk(node: ast.AST, scope: List[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                traced = (_jit_decorated(child, imports)
                          or child.name in scan_bodies)
                if traced:
                    params = {a.arg for a in child.args.args
                              + child.args.posonlyargs
                              + child.args.kwonlyargs}
                    params.discard("self")
                    v = _TracedRegionVisitor(
                        path, imports, scope + [child.name], params)
                    for stmt in child.body:
                        v.visit(stmt)
                    out.extend(v.out)
                else:
                    walk(child, scope + [child.name])
            elif isinstance(child, ast.ClassDef):
                walk(child, scope + [child.name])
            else:
                walk(child, scope)

    walk(tree, [])
    return out


# -------------------------------------------- rule: api-idempotency

_POST_METHODS = {"create", "create_batch", "create_from_template",
                 "bind", "bind_batch", "bind_batch_hosts"}


#: exception types whose explicit handling makes a POST replay
#: name-guarded: a re-sent create of the same name collapses on
#: AlreadyExists/Conflict instead of committing a duplicate
_REPLAY_GUARDS = {"AlreadyExists", "Conflict"}


def _names_in(node: ast.AST) -> set:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


class _IdempotencyVisitor(_ScopedVisitor):
    """A loop that try/excepts a bare POST verb and swallows the error
    is a client-side replay of a non-idempotent request: an ambiguous
    connection loss (request committed, response lost) duplicates the
    object. Retries belong in api/retry.py (which never replays a bare
    POST on ambiguity) — or the handler must catch
    AlreadyExists/Conflict, proving the create is name-guarded so a
    replay collapses instead of duplicating.

    A `for` loop whose POST arguments derive from the iteration
    variable is iteration, not retry (each pass posts a DIFFERENT
    object) and is not flagged."""

    RULE = "api-idempotency"

    def _post_calls_in(self, node: ast.AST):
        """POST-verb calls under `node`, NOT descending into nested
        Trys that carry their own replay guard (the guarded inner try
        answers for its calls)."""
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.Try) \
                    and any(self._guarded(h) for h in child.handlers):
                continue
            if isinstance(child, ast.Call) \
                    and isinstance(child.func, ast.Attribute) \
                    and child.func.attr in _POST_METHODS:
                receiver = _dotted(child.func.value) or ""
                # server-side registry/store writes are in-process
                # commits, not wire POSTs
                if receiver.split(".")[-1] not in ("registry", "store"):
                    yield child
            yield from self._post_calls_in(child)

    @staticmethod
    def _per_iteration(loop: ast.AST, call: ast.Call) -> bool:
        """True when the call's arguments depend on the loop targets —
        directly, through in-loop assignments, or through nested loop
        targets iterating over tainted values."""
        if not isinstance(loop, ast.For):
            return False
        tainted = _names_in(loop.target)
        for _ in range(8):  # taint to a fixpoint (chains are short)
            grown = set(tainted)
            for n in ast.walk(loop):
                if isinstance(n, ast.Assign) \
                        and _names_in(n.value) & grown:
                    for t in n.targets:
                        grown |= _names_in(t)
                elif isinstance(n, ast.For) and n is not loop \
                        and _names_in(n.iter) & grown:
                    grown |= _names_in(n.target)
            if grown == tainted:
                break
            tainted = grown
        args = set()
        for a in list(call.args) + [kw.value for kw in call.keywords]:
            args |= _names_in(a)
        return bool(args & tainted)

    @staticmethod
    def _guarded(handler: ast.ExceptHandler) -> bool:
        if handler.type is None:
            return False
        types = (handler.type.elts
                 if isinstance(handler.type, ast.Tuple)
                 else [handler.type])
        for t in types:
            name = _dotted(t) or ""
            if name.split(".")[-1] in _REPLAY_GUARDS:
                return True
        return False

    def _loop(self, node) -> None:
        for child in ast.walk(node):
            if not isinstance(child, ast.Try):
                continue
            if any(self._guarded(h) for h in child.handlers):
                continue  # name-guarded: replay collapses
            swallows = any(not any(isinstance(x, ast.Raise)
                                   for x in ast.walk(h))
                           for h in child.handlers)
            if not swallows:
                continue
            for call in self._post_calls_in(child):
                if self._per_iteration(node, call):
                    continue
                self.flag(self.RULE, call, "bare-post-retry-loop",
                          f".{call.func.attr}() retried in a loop with "
                          f"a swallowing except: an ambiguous failure "
                          f"replays a non-idempotent POST (duplicate "
                          f"objects); route it through RetryPolicy or "
                          f"catch AlreadyExists/Conflict as the replay "
                          f"guard")
        self.generic_visit(node)

    visit_For = _loop
    visit_While = _loop


def check_api_idempotency(tree: ast.AST, path: str) -> List[Violation]:
    v = _IdempotencyVisitor(path, _import_table(tree))
    v.visit(tree)
    return v.out


# -------------------------------------------------- rule: metric-pinning

#: registry read methods whose first argument is a metric name — the
#: calls a soak gate or SLO evaluation makes against a MetricsRegistry
_METRIC_READERS = {"counter", "counter_sum", "summary", "summary_stats",
                   "summary_samples", "histogram", "histogram_merged",
                   "histogram_stats"}

#: SLO-definition keyword args that carry metric names
_SLO_METRIC_KWARGS = {"metric", "good_metric"}

_PINNED_NAMES: Optional[frozenset] = None


def pinned_metric_names() -> frozenset:
    """The no-drift metric-name contract, read from utils/metrics.py
    by AST (not import): every string pinned in a module-level
    ALL_CAPS constant — bare string, tuple/list of strings, or dict
    key (HISTOGRAM_BUCKETS). Cached for the lint run's lifetime."""
    global _PINNED_NAMES
    if _PINNED_NAMES is not None:
        return _PINNED_NAMES
    src = os.path.join(os.path.dirname(__file__), os.pardir,
                       "utils", "metrics.py")
    with open(src, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=src)
    consts: Dict[str, str] = {}
    names: set = set()

    def _str(node) -> Optional[str]:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        if isinstance(node, ast.Name):
            return consts.get(node.id)
        return None

    for stmt in tree.body:
        if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
            continue
        targets = stmt.targets if isinstance(stmt, ast.Assign) \
            else [stmt.target]
        value = stmt.value
        if value is None or len(targets) != 1 \
                or not isinstance(targets[0], ast.Name) \
                or not targets[0].id.isupper():
            continue
        s = _str(value)
        if s is not None:
            consts[targets[0].id] = s
            names.add(s)
        elif isinstance(value, (ast.Tuple, ast.List)):
            names.update(s for s in map(_str, value.elts) if s)
        elif isinstance(value, ast.Dict):
            names.update(s for s in map(_str, value.keys) if s)
    _PINNED_NAMES = frozenset(names)
    return _PINNED_NAMES


def _metrics_imports(tree: ast.AST) -> set:
    """Local names bound by `from ...utils.metrics import X` (any
    relative level — _import_table skips those). A name whose
    provenance IS the pin module is pinned by construction."""
    out: set = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module \
                and node.module.split(".")[-1] == "metrics":
            out.update(a.asname or a.name for a in node.names
                       if a.name != "*")
    return out


class _MetricPinningVisitor(_ScopedVisitor):
    """A soak gate or SLO definition that reads a metric name not
    pinned in utils/metrics.py is one rename away from silently
    gating on a counter nobody increments (the DURABILITY_COUNTERS
    lesson, generalized). Names that cannot be resolved statically
    (loop variables, f-strings) are skipped — the rule is a tripwire
    for the common literal case, not a type system."""

    RULE = "metric-pinning"

    def __init__(self, path: str, imports: Dict[str, str],
                 consts: Dict[str, str], from_pin_module: set):
        super().__init__(path, imports)
        self.consts = consts
        self.from_pin_module = from_pin_module

    def _metric_name(self, node: ast.AST) -> Optional[str]:
        """Statically-resolved metric-name string, or None when the
        arg is unresolvable or pinned by import provenance."""
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        if isinstance(node, ast.Name) \
                and node.id not in self.from_pin_module:
            return self.consts.get(node.id)
        return None

    def _check(self, node: ast.AST, arg: ast.AST, what: str) -> None:
        name = self._metric_name(arg)
        if name is not None and name not in pinned_metric_names():
            self.flag(self.RULE, node, "unpinned-metric-name",
                      f"{what} reads metric {name!r}, which is not "
                      f"pinned in utils/metrics.py; add it to a "
                      f"module-level constant there (the no-drift "
                      f"contract: gates and dashboards must share "
                      f"one spelling)")

    def visit_Call(self, node: ast.Call) -> None:
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in _METRIC_READERS and node.args:
            self._check(node, node.args[0], f".{node.func.attr}()")
        callee = (_dotted(node.func) or "").split(".")[-1]
        if callee == "SLODef":
            for kw in node.keywords:
                if kw.arg in _SLO_METRIC_KWARGS:
                    self._check(node, kw.value, f"SLODef({kw.arg}=)")
        self.generic_visit(node)


def check_metric_pinning(tree: ast.AST, path: str) -> List[Violation]:
    from_pin = _metrics_imports(tree)
    consts: Dict[str, str] = {}
    for stmt in getattr(tree, "body", []):
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name):
            if isinstance(stmt.value, ast.Constant) \
                    and isinstance(stmt.value.value, str):
                consts[stmt.targets[0].id] = stmt.value.value
            elif isinstance(stmt.value, ast.Name) \
                    and stmt.value.id in from_pin:
                # alias of a pin-module import keeps its provenance
                from_pin.add(stmt.targets[0].id)
    v = _MetricPinningVisitor(path, _import_table(tree), consts, from_pin)
    v.visit(tree)
    return v.out


# ------------------------------------------------------ rule: shard-sync

#: call heads that PRODUCE a jitted dispatcher when assigned: the value
#: bound is a compiled callable whose outputs live on device (sharded
#: under a mesh)
_JIT_PRODUCERS = ("jax.jit", "jax.pmap")

#: attribute receivers that ARE jitted dispatchers on the engine
_DISPATCH_ATTRS = ("self._run", "self._scatter")


def _assigned_names(node: ast.AST) -> set:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def check_shard_sync(tree: ast.AST, path: str) -> List[Violation]:
    """Cross-shard host syncs in the tile loop.

    The live pipeline's contract: inside a per-tile/per-chunk Python
    loop, outputs of jitted dispatch (sharded jax.Arrays under a mesh)
    must stay on device — `jax.device_get`, `np.asarray`, `.item()`,
    `float()`/`int()`/`bool()` on them force a cross-shard gather +
    host sync per iteration, and a Python `if`/`while` on a per-shard
    value blocks the async dispatch queue the same way. Collect device
    references and pull ONCE after the loop (see
    engine.run_chunked's multiproc concat).

    Taint is name-level per scope: names bound from `jax.jit(...)` /
    `self._get_run(...)` / `self._runs.get(...)` are dispatchers;
    names bound from CALLING a dispatcher (tuple unpack included) are
    device values, propagated through assignments and list appends.
    `jax.device_get` inside a loop is flagged unconditionally — there
    is no loop in this tree where a per-iteration device_get is not a
    sync."""
    imports = _import_table(tree)
    out: List[Violation] = []

    def iter_own(node: ast.AST):
        """Descendants of `node`, not crossing into nested def/class
        scopes (their taint sets are their own)."""
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                continue
            yield child
            yield from iter_own(child)

    def process_scope(scope_node: ast.AST, scope: List[str]) -> None:
        nodes = list(iter_own(scope_node))
        jit_fns: set = set()
        tainted: set = set()

        def is_producer(call: ast.Call) -> bool:
            name = _resolve(call.func, imports)
            dotted = _dotted(call.func) or ""
            return (name in _JIT_PRODUCERS
                    or dotted.endswith("._get_run")
                    or dotted == "self._runs.get")

        def is_dispatch(call: ast.Call) -> bool:
            if isinstance(call.func, ast.Name) \
                    and call.func.id in jit_fns:
                return True
            return (_dotted(call.func) or "") in _DISPATCH_ATTRS

        for _ in range(8):  # taint to a fixpoint (chains are short)
            before = (len(jit_fns), len(tainted))
            for n in nodes:
                if isinstance(n, ast.Assign):
                    targets: set = set()
                    for t in n.targets:
                        targets |= _assigned_names(t)
                    calls = [c for c in ast.walk(n.value)
                             if isinstance(c, ast.Call)]
                    if any(is_producer(c) for c in calls):
                        jit_fns |= targets
                    elif any(is_dispatch(c) for c in calls) \
                            or (_assigned_names(n.value) & tainted):
                        tainted |= targets
                elif isinstance(n, ast.Call) \
                        and isinstance(n.func, ast.Attribute) \
                        and n.func.attr in ("append", "extend") \
                        and isinstance(n.func.value, ast.Name) \
                        and any(_assigned_names(a) & tainted
                                for a in n.args):
                    tainted.add(n.func.value.id)
            if (len(jit_fns), len(tainted)) == before:
                break

        site = ".".join(scope) or "<module>"

        def flag(node: ast.AST, symbol: str, message: str) -> None:
            out.append(Violation(
                rule="shard-sync", path=path,
                line=getattr(node, "lineno", 0),
                col=getattr(node, "col_offset", 0),
                site=site, symbol=symbol, message=message))

        def touches(node: ast.AST) -> bool:
            return bool(_assigned_names(node) & tainted)

        for loop in (n for n in nodes if isinstance(n, (ast.For,
                                                        ast.While))):
            if isinstance(loop, ast.While) and touches(loop.test):
                flag(loop, "branch-on-per-shard-value",
                     "Python `while` on a device value syncs every "
                     "shard to host per iteration; use host metadata "
                     "or fold the predicate into the jitted step")
            for n in iter_own(loop):
                if isinstance(n, ast.If) and touches(n.test):
                    flag(n, "branch-on-per-shard-value",
                         "Python `if` on a device value inside the "
                         "tile loop forces a cross-shard gather + "
                         "host sync per tile; branch on host "
                         "metadata or use jnp.where/lax.cond")
                elif isinstance(n, ast.While) and touches(n.test):
                    flag(n, "branch-on-per-shard-value",
                         "Python `while` on a device value inside "
                         "the tile loop syncs per iteration; use "
                         "lax.while_loop or host metadata")
                elif isinstance(n, ast.Call):
                    resolved = _resolve(n.func, imports)
                    if resolved == "jax.device_get":
                        flag(n, "device-get-in-tile-loop",
                             "jax.device_get inside the tile loop "
                             "gathers every shard to host per "
                             "iteration; collect device references "
                             "and pull once after the loop")
                    elif resolved in ("numpy.asarray", "numpy.array") \
                            and any(touches(a) for a in n.args):
                        flag(n, "host-pull-in-tile-loop",
                             f"{resolved.replace('numpy', 'np')}() on "
                             f"a device value inside the tile loop "
                             f"is a cross-shard host pull per tile; "
                             f"collect device references and "
                             f"transfer once after the loop")
                    elif resolved in ("float", "int", "bool") \
                            and any(touches(a) for a in n.args):
                        flag(n, "host-scalar-in-tile-loop",
                             f"{resolved}() on a device value inside "
                             f"the tile loop is a per-tile host "
                             f"sync; keep the scalar on device or "
                             f"pull after the loop")
                    elif isinstance(n.func, ast.Attribute) \
                            and n.func.attr == "item" \
                            and touches(n.func.value):
                        flag(n, "host-scalar-in-tile-loop",
                             ".item() on a device value inside the "
                             "tile loop is a per-tile cross-shard "
                             "sync; keep the scalar on device or "
                             "pull after the loop")

    def walk(node: ast.AST, scope: List[str]) -> None:
        process_scope(node, scope)
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef, ast.ClassDef)):
                walk(child, scope + [child.name])
            elif not isinstance(child, (ast.For, ast.While, ast.If,
                                        ast.With, ast.Try)):
                continue
            else:
                walk_nested_defs(child, scope)

    def walk_nested_defs(node: ast.AST, scope: List[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef, ast.ClassDef)):
                walk(child, scope + [child.name])
            else:
                walk_nested_defs(child, scope)

    walk(tree, [])
    return out


# ----------------------------------------------------------- the runner

def _soak_file(name: str) -> bool:
    return name.endswith("_soak.py")


def _rule_applies(rule: str, path: str) -> bool:
    """Scope map — paths are repo-relative posix."""
    if rule == "determinism":
        # obs/ is in the family: span IDs must stay a pure function of
        # (seed, counter) and timestamps must ride the injectable Clock
        # — wall-clock or process RNG there breaks the byte-identical
        # same-seed trace-export contract. leaderelection rides along
        # since the shard-lease protocol (sched/device/shardfail.py)
        # made lease liveness chaos-replayed state: a wall-clock read
        # there would break the FakeClock-driven expiry replay
        return (path.startswith("kubernetes_tpu/chaos/")
                or path.startswith("kubernetes_tpu/sched/")
                or path.startswith("kubernetes_tpu/obs/")
                or path == "kubernetes_tpu/utils/leaderelection.py"
                or (path.startswith("kubernetes_tpu/kubemark/")
                    and _soak_file(path.rsplit("/", 1)[-1])))
    if rule == "lock-discipline":
        return path in ("kubernetes_tpu/core/store.py",
                        "kubernetes_tpu/core/wal.py")
    if rule == "jax-hygiene":
        return path.startswith("kubernetes_tpu/sched/device/")
    if rule == "shard-sync":
        # the shard-kill soak drives the tile loop directly (dispatch,
        # epoch fence, reshard) — exactly where a per-tile host sync
        # would hide, so it joins the device modules in scope
        return (path.startswith("kubernetes_tpu/sched/device/")
                or path == "kubernetes_tpu/kubemark/shard_soak.py")
    if rule == "api-idempotency":
        return (path.startswith("kubernetes_tpu/")
                and path != "kubernetes_tpu/api/retry.py")
    if rule == "metric-pinning":
        # where gates and SLO definitions live: the soak harnesses and
        # the SLO module read metric names; everything else increments
        return path.startswith("kubernetes_tpu/kubemark/")
    raise ValueError(f"unknown rule {rule!r}")


RULES = {
    "determinism": check_determinism,
    "lock-discipline": check_lock_discipline,
    "jax-hygiene": check_jax_hygiene,
    "shard-sync": check_shard_sync,
    "api-idempotency": check_api_idempotency,
    "metric-pinning": check_metric_pinning,
}


def lint_source(src: str, path: str,
                rules: Optional[List[str]] = None) -> List[Violation]:
    """Lint one module's source. `path` (repo-relative posix) selects
    which rules apply; pass `rules` to force a specific set regardless
    of path (the test fixtures do)."""
    tree = ast.parse(src, filename=path)
    out: List[Violation] = []
    for rule, check in RULES.items():
        if rules is not None:
            if rule in rules:
                out.extend(check(tree, path))
        elif _rule_applies(rule, path):
            out.extend(check(tree, path))
    # a site inside nested loops/withs is reachable by more than one
    # enclosing construct — it is still ONE violation
    out = sorted(set(out), key=lambda v: (v.path, v.line, v.col, v.rule,
                                          v.symbol))
    return out


def lint_file(abspath: str, relpath: str) -> List[Violation]:
    with open(abspath, encoding="utf-8") as f:
        return lint_source(f.read(), relpath)


def _iter_py_files(root: str):
    pkg = os.path.join(root, "kubernetes_tpu")
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for name in sorted(filenames):
            if name.endswith(".py"):
                abspath = os.path.join(dirpath, name)
                rel = os.path.relpath(abspath, root).replace(os.sep, "/")
                yield abspath, rel


def repo_root() -> str:
    """The directory holding the kubernetes_tpu package."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def run_lint(root: Optional[str] = None,
             baseline_path: Optional[str] = None) -> LintReport:
    """Lint the tree under `root` and reconcile against the baseline.

    New violations (beyond the counted allowance) and stale baseline
    entries (allowance exceeding what the tree still contains) both
    fail — the allowlist can only shrink truthfully."""
    import time as _time
    t0 = _time.monotonic()
    root = root or repo_root()
    baseline = load_baseline(
        baseline_path if baseline_path is not None else DEFAULT_BASELINE)
    report = LintReport()
    for abspath, rel in _iter_py_files(root):
        report.files_scanned += 1
        try:
            report.violations.extend(lint_file(abspath, rel))
        except SyntaxError as e:
            report.new.append(Violation(
                rule="parse", path=rel, line=e.lineno or 0, col=0,
                site="<module>", symbol="syntax-error", message=str(e)))
    new, stale = baseline.reconcile(report.violations)
    report.new.extend(new)
    report.stale = stale
    report.seconds = _time.monotonic() - t0
    return report
