"""Runtime lock-order witness — the dynamic half of the lock lint.

The AST rule in this package enforces the stage/ledger/publish split
LEXICALLY; what it cannot see is the cross-thread ACQUISITION ORDER.
The reference gets that from Go's race detector + `go vet -copylocks`;
this is the Python stand-in: wrap the locks under test in
`WitnessedLock`s sharing one `LockWitness`, run the workload (the fast
chaos soak does), and the witness records

  - the pairwise order graph: an edge A->B means some thread acquired
    B while holding A. Observing both A->B and B->A is a lock-order
    INVERSION — two threads doing that concurrently is a deadlock
    waiting for the right interleaving, even if this run got lucky.
    (The sanctioned store order is publish -> ledger, pinned by
    Store._watch_register; ledger -> publish would deadlock against
    it.)
  - per-lock hold times: the two-phase commit exists to keep the
    ledger lock hold bounded (fan-out runs after release). A
    hold-time budget turns "publish crept back under the ledger lock"
    into a test failure instead of a p99 regression three PRs later.

Reentrant acquisition (the ledger lock is an RLock) increments a
per-thread depth — no new edges, no hold-clock restart — so RLock
recursion never self-reports. `acquire(blocking=False)` that fails
records nothing.

Usage (what tests/test_chaos.py wires into the fast soak):

    witness = LockWitness()
    witness_store(store, witness)
    ... drive the workload ...
    witness.assert_clean(max_hold={"store.ledger": 0.5})
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

__all__ = ["LockWitness", "WitnessedLock", "witness_store"]


class WitnessedLock:
    """Wraps a Lock/RLock, reporting acquire/release to the witness.
    Supports the full lock protocol the store uses: context manager,
    acquire(blocking=, timeout=), release."""

    def __init__(self, inner, name: str, witness: "LockWitness"):
        self._inner = inner
        self.name = name
        self._witness = witness

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._witness._acquired(self.name)
        return ok

    def release(self) -> None:
        self._witness._released(self.name)
        self._inner.release()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()


class LockWitness:
    """Shared recorder: order graph, inversions, hold times."""

    def __init__(self):
        self._mu = threading.Lock()  # leaf lock: guards only bookkeeping
        #: (held, acquired) -> "thread:held->acquired" of first sighting
        self._edges: Dict[Tuple[str, str], str] = {}
        #: observed inversions: ((a, b), first_sighting, second_sighting)
        self.inversions: List[Tuple[Tuple[str, str], str, str]] = []
        #: thread ident -> [(lock name, depth, t0)]
        self._held: Dict[int, List[list]] = {}
        #: lock name -> [acquisitions, max hold seconds]
        self._stats: Dict[str, list] = {}
        #: fired (outside _mu) the first time an inversion is
        #: recorded — the flight-recorder hook: a harness sets this to
        #: dump a post-mortem bundle at the instant of the sighting,
        #: when both stack's locks are still held and the span buffer
        #: still shows who took them
        self.on_inversion = None

    def wrap(self, lock, name: str) -> WitnessedLock:
        return WitnessedLock(lock, name, self)

    # ---------------------------------------------------------- recording

    def _acquired(self, name: str) -> None:
        ident = threading.get_ident()
        tname = threading.current_thread().name
        now = time.monotonic()
        first_inversion = False
        with self._mu:
            held = self._held.setdefault(ident, [])
            for entry in held:
                if entry[0] == name:      # reentrant: depth only
                    entry[1] += 1
                    return
            for prior, _depth, _t0 in held:
                edge = (prior, name)
                sighting = f"{tname}: {prior} -> {name}"
                self._edges.setdefault(edge, sighting)
                rev = self._edges.get((name, prior))
                if rev is not None:
                    first_inversion = not self.inversions
                    self.inversions.append(((name, prior), rev,
                                            sighting))
            held.append([name, 1, now])
            self._stats.setdefault(name, [0, 0.0])[0] += 1
        if first_inversion and self.on_inversion is not None:
            # outside _mu: the hook dumps a bundle (file I/O) and may
            # read report(), which takes _mu itself
            try:
                self.on_inversion()
            except Exception:
                pass  # a broken recorder must not break the workload

    def _released(self, name: str) -> None:
        now = time.monotonic()
        with self._mu:
            held = self._held.get(threading.get_ident(), [])
            for i, entry in enumerate(held):
                if entry[0] != name:
                    continue
                entry[1] -= 1
                if entry[1] == 0:
                    hold = now - entry[2]
                    stats = self._stats.setdefault(name, [0, 0.0])
                    stats[1] = max(stats[1], hold)
                    del held[i]
                return
            # released by a thread that did not acquire (legal for a
            # bare Lock, unused by the store): nothing to unwind

    # ---------------------------------------------------------- reporting

    def report(self) -> dict:
        with self._mu:
            return {
                "locks": {name: {"acquisitions": c,
                                 "max_hold_s": round(h, 6)}
                          for name, (c, h) in sorted(self._stats.items())},
                "edges": sorted(f"{a} -> {b}" for a, b in self._edges),
                "inversions": [
                    {"pair": list(pair), "first": first, "second": second}
                    for pair, first, second in self.inversions],
            }

    def assert_clean(self,
                     max_hold: Optional[Dict[str, float]] = None) -> None:
        """Raise AssertionError on any recorded inversion, or on a
        lock whose max observed hold exceeded its budget."""
        rep = self.report()
        problems = [f"lock-order inversion {inv['pair']}: "
                    f"{inv['first']} vs {inv['second']}"
                    for inv in rep["inversions"]]
        for name, budget in sorted((max_hold or {}).items()):
            seen = rep["locks"].get(name, {}).get("max_hold_s", 0.0)
            if seen > budget:
                problems.append(
                    f"{name}: max hold {seen:.4f}s exceeds the "
                    f"{budget:.4f}s budget (publish creeping back "
                    f"under the ledger lock?)")
        if problems:
            raise AssertionError(
                "lock witness: " + "; ".join(problems)
                + f" [report: {rep}]")


def witness_store(store, witness: Optional[LockWitness] = None
                  ) -> LockWitness:
    """Swap a Store's ledger and publish locks for witnessed wrappers
    (do this BEFORE the store serves traffic). Returns the witness.
    Lock names: `store.ledger`, `store.publish`."""
    witness = witness or LockWitness()
    store._lock = witness.wrap(store._lock, "store.ledger")
    store._pub_lock = witness.wrap(store._pub_lock, "store.publish")
    return witness
