"""Node-plane agents: hollow kubelet (kubemark-style), status manager.

The reference proves master-plane parity with hollow nodes — real kubelet
code against fake runtimes (pkg/kubemark/hollow_kubelet.go). We take the
same stance: the node agent's contract with the control plane (register,
heartbeat, watch assigned pods, report status) is implemented for real;
the container runtime behind it is a fake that "runs" pods instantly.
"""

from .hollow_node import HollowKubelet, StatusManager, FakeRuntime

__all__ = ["HollowKubelet", "StatusManager", "FakeRuntime"]
