"""Hollow kubelet: the node agent with a fake container runtime.

Reference behavior being reproduced (not the Go structure):
- register the Node object and heartbeat NodeStatus conditions
  (pkg/kubelet/kubelet.go registerWithApiserver / tryUpdateNodeStatus;
  conditions Ready + OutOfDisk are what the scheduler's node filter reads,
  plugin/pkg/scheduler/factory/factory.go:241-256)
- watch pods bound to this node via the spec.nodeName field selector
  (kubelet's apiserver pod source, pkg/kubelet/config/apiserver.go)
- a sync loop starts/stops "containers" through a Runtime interface
  (pkg/kubelet/container Runtime); kubemark swaps in a fake that succeeds
  instantly (pkg/kubemark/hollow_kubelet.go:35-80, FakeDockerClient)
- a status manager syncs PodStatus to the apiserver in batches, skipping
  no-op updates (pkg/kubelet/status/manager.go:117-146 syncBatch)

MaxPods defaults to 40 per hollow node (hollow_kubelet.go:73).
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import replace
from typing import Dict, List, Optional

from ..api.cache import Informer, meta_namespace_key
from ..api.client import confirm_pod_deletion
from ..core import types as api
from ..core.errors import NotFound
from ..core.quantity import Quantity, parse_quantity
from ..utils.clock import Clock, RealClock


class FakeRuntime:
    """Instant-success container runtime (kubemark's FakeDockerClient
    analogue). Tracks which pods are "running" so tests can assert."""

    def __init__(self):
        self._running: Dict[str, List[api.ContainerStatus]] = {}
        self._pods: Dict[str, api.Pod] = {}  # key -> latest pod object
        self._lock = threading.Lock()

    def run_pod(self, pod: api.Pod) -> List[api.ContainerStatus]:
        key = pod_key(pod)
        with self._lock:
            self._pods[key] = pod
            # already running: report the existing containers so started_at
            # stays stable across resyncs (a real runtime wouldn't restart)
            if key in self._running:
                return list(self._running[key])
            ts = api.now_rfc3339()
            statuses = [api.ContainerStatus(
                name=c.name, ready=True, image=c.image,
                container_id=f"fake://{pod.metadata.uid}/{c.name}",
                state=api.ContainerState(
                    running=api.ContainerStateRunning(started_at=ts)))
                for c in pod.spec.containers]
            self._running[key] = statuses
            return list(statuses)

    def kill_pod(self, pod: api.Pod) -> None:
        with self._lock:
            self._running.pop(pod_key(pod), None)
            self._pods.pop(pod_key(pod), None)

    def running_pods(self) -> List[str]:
        with self._lock:
            return list(self._running)

    def pods(self) -> List[api.Pod]:
        """Latest bound-pod objects (the KubeletServer /pods source)."""
        with self._lock:
            return list(self._pods.values())

    # -- kubelet-server seam (kubelet/server.py KubeletServer.runtime) --

    def get_pods(self):
        """The runtime's view in kubecontainer.Pod shape
        (ref: kubecontainer.Runtime.GetPods)."""
        from ..kubelet.container import RuntimeContainer, RuntimePod
        out = []
        with self._lock:
            for key, statuses in self._running.items():
                pod = self._pods.get(key)
                if pod is None:
                    continue
                out.append(RuntimePod(
                    uid=pod.metadata.uid, name=pod.metadata.name,
                    namespace=pod.metadata.namespace,
                    containers=[RuntimeContainer(
                        id=cs.container_id, name=cs.name, image=cs.image)
                        for cs in statuses]))
        return out

    def get_container_logs(self, pod_uid: str, name: str,
                           tail_lines: int = 0,
                           previous: bool = False) -> str:
        if previous:
            raise KeyError('hollow runtime keeps no previous logs')
        with self._lock:
            for key, pod in self._pods.items():
                if pod.metadata.uid != pod_uid or key not in self._running:
                    continue
                if any(cs.name == name for cs in self._running[key]):
                    from ..kubelet.container import tail_text
                    return tail_text(
                        f"hollow logs for {pod.metadata.name}/{name}\n",
                        tail_lines)
        raise KeyError(f"container {name!r} not found")

    def exec_in_container(self, pod_uid: str, name: str, cmd):
        with self._lock:
            known = any(
                pod.metadata.uid == pod_uid
                and any(cs.name == name for cs in self._running.get(key, []))
                for key, pod in self._pods.items())
        if not known:
            raise KeyError(f"container {name!r} not found")
        return 0, f"hollow exec: {' '.join(cmd)}\n"


pod_key = meta_namespace_key


class StatusManager:
    """Batches PodStatus writes to the apiserver, dropping duplicates
    (ref: pkg/kubelet/status/manager.go SetPodStatus :117 /
    syncBatch :134)."""

    def __init__(self, client):
        self.client = client
        self._statuses: Dict[str, api.PodStatus] = {}
        self._queue: "queue.Queue[Optional[api.Pod]]" = queue.Queue()
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None

    def set_pod_status(self, pod: api.Pod, status: api.PodStatus) -> None:
        key = pod_key(pod)
        with self._lock:
            if self._statuses.get(key) == status:
                return  # no-op update elided (manager.go:127)
            self._statuses[key] = status
        self._queue.put(replace(pod, status=status))

    def forget(self, pod: api.Pod) -> None:
        with self._lock:
            self._statuses.pop(pod_key(pod), None)

    def start(self) -> "StatusManager":
        self._thread = threading.Thread(target=self._sync_loop, daemon=True,
                                        name="status-manager")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._queue.put(None)

    def _sync_loop(self) -> None:
        while True:
            pod = self._queue.get()
            if pod is None:
                return
            try:
                self.client.update_status("pods", pod,
                                          pod.metadata.namespace)
            except NotFound:
                with self._lock:
                    self._statuses.pop(pod_key(pod), None)
            except Exception:
                # transient apiserver failure: no watch event will re-drive
                # an unchanged pod, so requeue until it lands or the pod
                # disappears (manager.go retries on the next sync tick)
                time.sleep(0.2)
                with self._lock:
                    still_wanted = pod_key(pod) in self._statuses
                if still_wanted:
                    self._queue.put(pod)


class HollowKubelet:
    """One hollow node: Node registration + heartbeat + pod sync loop."""

    def __init__(self, client, node_name: str,
                 cpu: str = "4", memory: str = "32Gi", max_pods: int = 40,
                 heartbeat_interval: float = 10.0,
                 clock: Optional[Clock] = None,
                 runtime: Optional[FakeRuntime] = None,
                 labels: Optional[Dict[str, str]] = None,
                 serve_http: bool = False):
        self.client = client
        self.node_name = node_name
        self.cpu = cpu
        self.memory = memory
        self.max_pods = max_pods
        self.heartbeat_interval = heartbeat_interval
        self.clock = clock or RealClock()
        self.runtime = runtime or FakeRuntime()
        self.labels = dict(labels or {})
        self.status_manager = StatusManager(client)
        self._informer: Optional[Informer] = None
        self._stop = threading.Event()
        # the node's remote surface (ref: hollow nodes run the REAL
        # kubelet server in kubemark, hollow_kubelet.go:35); port lands
        # in NodeStatus.daemon_endpoints for the apiserver proxy
        # allocatable accounting (stub: no reservations, hollow-node.go:101)
        from ..kubelet.cm import stub_container_manager
        self.container_manager = stub_container_manager()
        self.server = None
        if serve_http:
            from ..kubelet.server import KubeletServer
            self.server = KubeletServer(
                node_name, self.runtime.pods, self.runtime,
                self._capacity,
                container_manager=self.container_manager)
        # registration/heartbeat machinery shared with the real kubelet
        # process (kubelet/registration.py)
        from ..kubelet.registration import NodeRegistration
        self._registration = NodeRegistration(
            client, node_name, self._capacity,
            allocatable=lambda: self.container_manager.allocatable(
                self._capacity()),
            daemon_port=lambda: (self.server.port
                                 if self.server is not None else 0),
            host=(self.server.host if self.server is not None
                  else "127.0.0.1"),
            heartbeat_interval=heartbeat_interval,
            labels=self.labels, kubelet_version="hollow",
            runtime_version="fake://0")

    # -- node object ------------------------------------------------------

    def _capacity(self) -> Dict[str, Quantity]:
        return {"cpu": parse_quantity(self.cpu),
                "memory": parse_quantity(self.memory),
                "pods": parse_quantity(str(self.max_pods))}

    def register(self) -> None:
        self._registration.register()

    def _heartbeat_once(self) -> None:
        self._registration.heartbeat_once()

    # -- pod sync ---------------------------------------------------------

    def _sync_pod(self, pod: api.Pod) -> None:
        if pod.status.phase in ("Succeeded", "Failed"):
            return
        statuses = self.runtime.run_pod(pod)
        status = api.PodStatus(
            phase="Running",
            conditions=[api.PodCondition(type="Ready", status="True")],
            host_ip="10.0.0.1", pod_ip="10.244.0.2",
            start_time=pod.status.start_time or api.now_rfc3339(),
            container_statuses=statuses)
        self.status_manager.set_pod_status(pod, status)

    def _on_pod_add(self, pod: api.Pod) -> None:
        if pod.metadata.deletion_timestamp is not None:
            self._confirm_deletion(pod)
            return
        self._sync_pod(pod)

    def _on_pod_update(self, old: api.Pod, pod: api.Pod) -> None:
        if pod.metadata.deletion_timestamp is not None:
            self._confirm_deletion(pod)
            return
        self._sync_pod(pod)

    def _confirm_deletion(self, pod: api.Pod) -> None:
        """Graceful deletion's node half, hollow style: no real
        containers to drain, so kill the fake pod and confirm with the
        grace-0 uid-guarded delete immediately (the real kubelet's
        handle_pod_update drain, minus the PreStop wait)."""
        self.runtime.kill_pod(pod)
        self.status_manager.forget(pod)
        confirm_pod_deletion(self.client, pod)

    def _on_pod_delete(self, pod: api.Pod) -> None:
        self.runtime.kill_pod(pod)
        self.status_manager.forget(pod)

    # -- lifecycle --------------------------------------------------------

    def run(self) -> "HollowKubelet":
        if self.server is not None:
            self.server.start()
        self.status_manager.start()
        self._informer = Informer(
            self.client, "pods",
            field_selector=f"spec.nodeName={self.node_name}",
            on_add=self._on_pod_add, on_update=self._on_pod_update,
            on_delete=self._on_pod_delete).start()
        self._registration.run()  # register + heartbeat loop
        return self

    def stop(self) -> None:
        self._stop.set()
        self._registration.stop()
        if self._informer:
            self._informer.stop()
        self.status_manager.stop()
        if self.server is not None:
            self.server.stop()
