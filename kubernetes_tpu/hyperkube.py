"""hyperkube — the all-in-one component multiplexer.

Reference: cmd/hyperkube/main.go:42 (one binary, component picked by the
first argument / argv[0] morph) and cmd/kubemark/hollow-node.go:80-130
(--morph). Run as:

    python -m kubernetes_tpu apiserver  --port 8080 --storage-backend native
    python -m kubernetes_tpu scheduler  --master http://127.0.0.1:8080 --mode batch
    python -m kubernetes_tpu controller-manager --master http://...
    python -m kubernetes_tpu hollow-node  --master http://... --name node-1
    python -m kubernetes_tpu hollow-fleet --master http://... --num-nodes 100
    python -m kubernetes_tpu kubectl  -s http://... get pods

Each long-running component prints one READY line to stdout
(`<component> ready <detail>`) once serving — process supervisors and the
multi-process tests key on it — then blocks until SIGTERM/SIGINT, stops
cleanly, and exits 0.
"""

from __future__ import annotations

import os
import argparse
import json
import signal
import sys
import threading
import time
import urllib.error
import urllib.request
from typing import List, Optional


def _pin_jax_platform() -> None:
    """Honor JAX_PLATFORMS even though the image's sitecustomize pins the
    platform at interpreter start (same re-pin tests/conftest.py makes):
    a scheduler child process launched with JAX_PLATFORMS=cpu must not
    grab the TPU out from under its parent."""
    import os
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax
        jax.config.update("jax_platforms", plat)


def _read_lines(path: Optional[str]) -> Optional[List[str]]:
    if not path:
        return None
    with open(path) as f:
        return f.read().splitlines()


def _wait_for_master(url: str, timeout_s: float = 60.0) -> None:
    """Block until the apiserver's /healthz answers (components race the
    master at process start; the reference's client retries likewise)."""
    deadline = time.time() + timeout_s
    last: Exception = RuntimeError("never tried")
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(url.rstrip("/") + "/healthz",
                                        timeout=5) as resp:
                if resp.status == 200:
                    return
        except (urllib.error.URLError, OSError) as e:
            last = e
        time.sleep(0.1)
    raise RuntimeError(f"master {url} not healthy after {timeout_s}s: {last}")


def _start_healthz(component: str):
    """Serve healthz/metrics on the component's conventional port
    (scheduler :10251 / controller-manager :10252, the ports the
    apiserver's componentstatus resource probes; ref: plugin/cmd/
    kube-scheduler/app/server.go:128-143). Best effort: a taken port
    (tests, multiple schedulers) disables the server rather than the
    component."""
    from .utils.healthz import (CONTROLLER_MANAGER_PORT, SCHEDULER_PORT,
                                HealthzServer)
    port = (SCHEDULER_PORT if component == "scheduler"
            else CONTROLLER_MANAGER_PORT)
    try:
        server = HealthzServer(port=port).start()
        return server.stop
    except OSError:
        return lambda: None


def _make_recorder(client, component: str, host: str = ""):
    """One event recorder posting to the apiserver (the per-binary
    EventBroadcaster wiring every reference component repeats)."""
    from .api.record import ClientEventSink, EventBroadcaster
    from .core import types as api
    return EventBroadcaster().start_recording_to_sink(
        ClientEventSink(client)).new_recorder(
        api.EventSource(component=component, host=host))


def _serve_until_signal(ready_line: str, stop_fns) -> int:
    """Print the READY line, then park until SIGTERM/SIGINT and unwind."""
    stop_event = threading.Event()

    def on_signal(signum, frame):
        stop_event.set()

    signal.signal(signal.SIGTERM, on_signal)
    signal.signal(signal.SIGINT, on_signal)
    print(ready_line, flush=True)
    stop_event.wait()
    for fn in stop_fns:
        try:
            fn()
        except Exception:
            pass
    return 0


# ------------------------------------------------------------- components

def _parse_runtime_config(spec: str) -> "dict | None":
    """'k1=false,k2,k3=true' -> {k1: False, k2: True, ...}; a bare key
    means true, matching the reference's ConfigurationMap.Set
    (pkg/util/configuration_map.go)."""
    out = {}
    for pair in spec.split(","):
        pair = pair.strip()
        if not pair:
            continue
        key, _, val = pair.partition("=")
        val = val.strip().lower()
        if val in ("", "true", "1"):
            out[key.strip()] = True
        elif val in ("false", "0"):
            out[key.strip()] = False
        else:
            # fail at startup like the reference's boolean parse; a typo
            # ("=flase") must not silently invert into the permissive
            # setting
            raise SystemExit(
                f"--runtime-config: invalid boolean {val!r} for "
                f"{key.strip()!r}")
    return out or None


def run_apiserver(argv: List[str]) -> int:
    """(ref: cmd/kube-apiserver/app/server.go:358 APIServer.Run)"""
    p = argparse.ArgumentParser(prog="apiserver")
    p.add_argument("--bind-address", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8080)
    p.add_argument("--storage-backend", choices=["memory", "native"],
                   default="memory")
    p.add_argument("--admission-control", default="",
                   help="ordered comma-separated plugin list "
                        "(ref: server.go:230)")
    p.add_argument("--basic-auth-file")
    p.add_argument("--token-auth-file")
    p.add_argument("--authorization-mode", default="AlwaysAllow",
                   choices=["AlwaysAllow", "AlwaysDeny", "ABAC"])
    p.add_argument("--authorization-policy-file")
    p.add_argument("--service-cluster-ip-range", default="10.0.0.0/24")
    p.add_argument("--max-requests-inflight", type=int, default=400)
    p.add_argument("--tls-cert-file", default="",
                   help="serve HTTPS (ref: --tls-cert-file)")
    p.add_argument("--tls-private-key-file", default="")
    p.add_argument("--client-ca-file", default="",
                   help="verify client certs against this CA and enable "
                        "x509 authentication (ref: --client-ca-file)")
    p.add_argument("--oidc-jwks-file", default="",
                   help="JWKS document for RS256 ID-token verification "
                        "(ref: --oidc-issuer-url + provider JWKS sync; "
                        "zero-egress stand-in for the discovery fetch)")
    p.add_argument("--oidc-issuer-url", default="")
    p.add_argument("--oidc-client-id", default="")
    p.add_argument("--oidc-username-claim", default="sub")
    p.add_argument("--oidc-groups-claim", default="groups")
    p.add_argument("--experimental-keystone-url", default="",
                   help="delegate basic-auth to a keystone v2 endpoint "
                        "(ref: --experimental-keystone-url)")
    p.add_argument("--runtime-config", default="",
                   help="comma-separated key=value pairs turning API "
                        "versions/resources on or off: api/v1, "
                        "apis/extensions/v1beta1, "
                        "apis/extensions/v1beta1/<resource>; api/all "
                        "and api/legacy are special keys "
                        "(ref: server.go:244)")
    args = p.parse_args(argv)

    from .master import Master, MasterConfig
    master = Master(MasterConfig(
        host=args.bind_address, port=args.port,
        storage_backend=args.storage_backend,
        admission_control=[s for s in args.admission_control.split(",") if s],
        basic_auth_lines=_read_lines(args.basic_auth_file),
        token_auth_lines=_read_lines(args.token_auth_file),
        authorization_mode=args.authorization_mode,
        authorization_policy_lines=_read_lines(args.authorization_policy_file),
        service_cidr=args.service_cluster_ip_range,
        max_in_flight=args.max_requests_inflight,
        runtime_config=_parse_runtime_config(args.runtime_config),
        tls_cert_file=args.tls_cert_file,
        tls_key_file=args.tls_private_key_file,
        tls_client_ca_file=args.client_ca_file,
        oidc_jwks=(json.load(open(args.oidc_jwks_file))
                   if args.oidc_jwks_file else None),
        oidc_issuer=args.oidc_issuer_url,
        oidc_client_id=args.oidc_client_id,
        oidc_username_claim=args.oidc_username_claim,
        oidc_groups_claim=args.oidc_groups_claim,
        keystone_url=args.experimental_keystone_url)).start()
    # freeze the booted master out of the young generations
    # (utils/gctune.py) — the fan-out/serve path churns small objects
    from .utils.gctune import tune_for_server
    tune_for_server()
    return _serve_until_signal(f"apiserver ready {master.url}",
                               [master.stop])


def run_scheduler(argv: List[str]) -> int:
    """(ref: plugin/cmd/kube-scheduler/app/server.go:49-187)"""
    p = argparse.ArgumentParser(prog="scheduler")
    p.add_argument("--master", required=True)
    p.add_argument("--mode", choices=["batch", "serial"], default="batch")
    p.add_argument("--policy-config-file")
    p.add_argument("--algorithm-provider", default="DefaultProvider")
    p.add_argument("--no-rate-limit", action="store_true",
                   help="disable the 50/s bind rate limit "
                        "(--bind-pods-qps equivalent)")
    args = p.parse_args(argv)

    # A dedicated scheduler process: its thread re-enters Python between
    # device dispatches, and CPython's default 5ms GIL slice makes each
    # re-entry wait behind watch/IO threads (measured ~10% of e2e wall
    # at kubemark scale). Process-wide by design — this process exists
    # to schedule.
    import sys as _sys
    _sys.setswitchinterval(0.001)
    # steady-state server GC posture (no cycles in the API types;
    # see utils/gctune.py for the measurement behind it)
    from .utils.gctune import tune_for_server
    tune_for_server()
    _pin_jax_platform()
    from .api.client import HttpClient
    from .sched.api import policy_from_json
    from .sched.batch import BatchScheduler
    from .sched.factory import ConfigFactory
    from .sched.scheduler import Scheduler

    _wait_for_master(args.master)
    client = HttpClient(args.master)
    # FailedScheduling and friends as first-class events (the reference
    # scheduler's recorder, scheduler.go Error func)
    factory = ConfigFactory(client, rate_limit=not args.no_rate_limit,
                            recorder=_make_recorder(
                                client, "scheduler")).start()

    policy = None
    if args.policy_config_file:
        with open(args.policy_config_file) as f:
            policy = policy_from_json(f.read())

    config = factory.create_batch(policy) if args.mode == "batch" else None
    if config is not None:
        sched = BatchScheduler(config).run()
    else:
        # the fast-path ladder: batch > mixed (device probe + HTTP
        # extenders) > serial — each rung a provable fallback
        mixed = (factory.create_mixed(policy)
                 if args.mode == "batch" else None)
        if mixed is not None:
            sched = Scheduler(mixed).run()
        else:
            sched = Scheduler(
                factory.create_from_config(policy) if policy
                else factory.create_from_provider(
                    args.algorithm_provider)).run()
    stops = [sched.stop, factory.stop]
    stops.append(_start_healthz("scheduler"))
    return _serve_until_signal(
        f"scheduler ready mode={args.mode}", stops)


def run_controller_manager(argv: List[str]) -> int:
    """(ref: cmd/kube-controller-manager/app/controllermanager.go:284)"""
    p = argparse.ArgumentParser(prog="controller-manager")
    p.add_argument("--master", required=True)
    p.add_argument("--allocate-node-cidrs", action="store_true",
                   help="assign each node a pod CIDR from "
                        "--cluster-cidr (controllermanager.go:228)")
    p.add_argument("--cluster-cidr", default="10.244.0.0/16")
    args = p.parse_args(argv)

    from .api.client import HttpClient
    from .controllers.manager import ControllerManager

    _wait_for_master(args.master)
    client = HttpClient(args.master)
    # controllers record first-class events (SuccessfulCreate, eviction
    # notices, ...) like the reference's per-controller recorders
    manager = ControllerManager(
        client, recorder=_make_recorder(client, "controller-manager"),
        allocate_node_cidrs=args.allocate_node_cidrs,
        cluster_cidr=args.cluster_cidr).run()
    return _serve_until_signal(
        "controller-manager ready",
        [manager.stop, _start_healthz("controller-manager")])


def run_kubelet(argv: List[str]) -> int:
    """The REAL kubelet process: subprocess runtime (pods as process
    groups), volumes, image manager, kubelet HTTP server, node
    registration + heartbeats, lifecycle events, cluster-DNS resolver
    config (ref: cmd/kubelet/app/server.go RunKubelet)."""
    p = argparse.ArgumentParser(prog="kubelet")
    p.add_argument("--master", required=True)
    p.add_argument("--name", required=True)
    p.add_argument("--root-dir", default="")
    p.add_argument("--port", type=int, default=0,
                   help="kubelet server port (0 = ephemeral)")
    p.add_argument("--cpu", default="4")
    p.add_argument("--memory", default="8Gi")
    p.add_argument("--max-pods", type=int, default=110)
    p.add_argument("--manifest-path", default="")
    p.add_argument("--manifest-url", default="")
    p.add_argument("--cluster-dns", default="")
    p.add_argument("--cluster-domain", default="")
    p.add_argument("--resolv-conf", default="/etc/resolv.conf")
    p.add_argument("--heartbeat-interval", type=float, default=10.0)
    p.add_argument("--network-plugin", default="",
                   help="network plugin name; empty = host-address "
                        "(process pods share the host netns, so the "
                        "node's own address is theirs)")
    p.add_argument("--node-ip", default="127.0.0.1",
                   help="this node's reachable address — the pod IP "
                        "the default network plugin reports")
    p.add_argument("--network-plugin-dir",
                   default="/usr/libexec/kubernetes/kubelet-plugins"
                           "/net/exec/",
                   help="exec plugin directory (exec.go contract)")
    p.add_argument("--node-log-dir", default="/var/log",
                   help="directory served at the kubelet's /logs/ "
                        "(server.go:303); empty disables")
    p.add_argument("--shaper-interface", default="",
                   help="enable tc bandwidth shaping on this interface "
                        "(kubernetes.io/{in,e}gress-bandwidth pod "
                        "annotations; needs tc + NET_ADMIN)")
    args = p.parse_args(argv)

    from .api.client import HttpClient
    from .core.quantity import parse_quantity
    from .kubelet import Kubelet
    from .kubelet.bandwidth import TCShaper
    from .kubelet.images import ImageManager
    from .kubelet.network import ExecNetworkPlugin, HostNetworkPlugin
    from .kubelet.registration import NodeRegistration
    from .kubelet.server import KubeletServer
    from .kubelet.subprocess_runtime import SubprocessRuntime
    from .volume.plugins import VolumeHost, new_default_plugin_mgr

    _wait_for_master(args.master)
    client = HttpClient(args.master)
    recorder = _make_recorder(client, "kubelet", host=args.name)
    runtime = SubprocessRuntime(args.root_dir or None)
    volume_root = os.path.join(runtime.root_dir, "volumes")

    def capacity():
        return {"cpu": parse_quantity(args.cpu),
                "memory": parse_quantity(args.memory),
                "pods": parse_quantity(str(args.max_pods))}

    kubelet = Kubelet(
        client, args.name, runtime=runtime,
        volume_mgr=new_default_plugin_mgr(
            VolumeHost(volume_root, client=client)),
        image_manager=ImageManager(recorder=recorder),
        manifest_path=args.manifest_path or None,
        manifest_url=args.manifest_url or None,
        cluster_dns=args.cluster_dns or None,
        cluster_domain=args.cluster_domain,
        resolver_config=args.resolv_conf,
        recorder=recorder,
        network_plugin=(ExecNetworkPlugin(args.network_plugin_dir,
                                          args.network_plugin)
                        if args.network_plugin
                        else HostNetworkPlugin(args.node_ip)),
        shaper=(TCShaper(args.shaper_interface)
                if args.shaper_interface else None))
    server = KubeletServer(args.name, kubelet.get_pods, runtime,
                           capacity, port=args.port,
                           node_log_dir=args.node_log_dir).start()
    registration = NodeRegistration(
        client, args.name, capacity,
        daemon_port=lambda: server.port, host=server.host,
        heartbeat_interval=args.heartbeat_interval).run()
    kubelet.run()
    return _serve_until_signal(
        f"kubelet ready {args.name} port={server.port}",
        [kubelet.stop, registration.stop, server.stop])


def run_hollow_node(argv: List[str]) -> int:
    """(ref: cmd/kubemark/hollow-node.go:80 --morph=kubelet)"""
    p = argparse.ArgumentParser(prog="hollow-node")
    p.add_argument("--master", required=True)
    p.add_argument("--name", required=True)
    p.add_argument("--cpu", default="4")
    p.add_argument("--memory", default="32Gi")
    p.add_argument("--max-pods", type=int, default=40)
    p.add_argument("--serve-http", action="store_true",
                   help="serve the kubelet HTTP surface (/pods /stats "
                        "/containerLogs ...) and register its port on "
                        "the Node (server.go:210)")
    args = p.parse_args(argv)

    from .agents.hollow_node import HollowKubelet
    from .api.client import HttpClient

    _wait_for_master(args.master)
    kubelet = HollowKubelet(HttpClient(args.master), args.name,
                            cpu=args.cpu, memory=args.memory,
                            max_pods=args.max_pods,
                            serve_http=args.serve_http).run()
    return _serve_until_signal(f"hollow-node ready {args.name}",
                               [kubelet.stop])


def run_hollow_fleet(argv: List[str]) -> int:
    """A fleet of hollow nodes in one process (ref: pkg/kubemark/ +
    test/kubemark/start-kubemark.sh: NUM_NODES hollow-node replicas)."""
    p = argparse.ArgumentParser(prog="hollow-fleet")
    p.add_argument("--master", required=True)
    p.add_argument("--num-nodes", type=int, default=100)
    p.add_argument("--name-prefix", default="hollow-")
    p.add_argument("--cpu", default="4")
    p.add_argument("--memory", default="32Gi")
    p.add_argument("--max-pods", type=int, default=40)
    p.add_argument("--heartbeat-interval", type=float, default=10.0)
    args = p.parse_args(argv)

    from .api.client import HttpClient
    from .kubemark.fleet import HollowFleet

    _wait_for_master(args.master)
    fleet = HollowFleet(HttpClient(args.master), args.num_nodes,
                        name_prefix=args.name_prefix, cpu=args.cpu,
                        memory=args.memory, max_pods=args.max_pods,
                        heartbeat_interval=args.heartbeat_interval).run()
    return _serve_until_signal(
        f"hollow-fleet ready nodes={args.num_nodes}", [fleet.stop])


def run_dns(argv: List[str]) -> int:
    """Cluster DNS (ref: cluster/addons/dns — the kube2sky + skydns
    pair as one informer-fed server; DIVERGENCES #16)."""
    p = argparse.ArgumentParser(prog="dns")
    p.add_argument("--master", required=True)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=10053)
    p.add_argument("--cluster-domain", default="cluster.local")
    p.add_argument("--upstream", default="",
                   help="host:port resolver for out-of-domain queries")
    args = p.parse_args(argv)

    from .api.client import HttpClient
    from .dns import ClusterDNS

    upstream = None
    if args.upstream:
        host, sep, port = args.upstream.rpartition(":")
        if not sep:
            host, port = args.upstream, "53"
        if not host or not port.isdigit():
            p.error(f"--upstream must be host[:port], got "
                    f"{args.upstream!r}")
        upstream = (host, int(port))
    _wait_for_master(args.master)
    dns = ClusterDNS(HttpClient(args.master), host=args.host,
                     port=args.port, cluster_domain=args.cluster_domain,
                     upstream=upstream).start()
    return _serve_until_signal(
        f"dns ready {args.host}:{dns.port} domain={args.cluster_domain}",
        [dns.stop])


def run_proxy(argv: List[str]) -> int:
    """(ref: cmd/kube-proxy + the hollow --morph=proxy,
    cmd/kubemark/hollow-node.go:80: fake iptables backing the real
    proxier code)"""
    p = argparse.ArgumentParser(prog="proxy")
    p.add_argument("--master", required=True)
    p.add_argument("--proxy-mode", choices=["iptables", "userspace"],
                   default="iptables")
    p.add_argument("--hollow", action="store_true",
                   help="fake iptables (the kubemark hollow-proxy morph; "
                        "without it, iptables mode execs the real binary "
                        "and needs netfilter privileges)")
    p.add_argument("--nodeport-bind-address", default="",
                   help="address NodePort listeners bind (userspace "
                        "mode); empty = all interfaces, like the "
                        "reference's claimNodePort — pass 127.0.0.1 to "
                        "keep node ports loopback-only (the kube-proxy "
                        "--bind-address role)")
    args = p.parse_args(argv)

    from .api.client import HttpClient
    from .proxy.iptables import ExecIPTables, FakeIPTables

    _wait_for_master(args.master)
    client = HttpClient(args.master)
    if args.proxy_mode == "userspace":
        from .proxy.userspace import UserspaceProxier
        proxier = UserspaceProxier(
            client, node_address=args.nodeport_bind_address).run()
    else:
        from .proxy.proxier import IPTablesProxier
        ipt = FakeIPTables() if args.hollow else ExecIPTables()
        proxier = IPTablesProxier(ipt, client).run()
    return _serve_until_signal(
        f"proxy ready mode={args.proxy_mode}"
        + (" hollow" if args.hollow else ""), [proxier.stop])


def run_kubectl(argv: List[str]) -> int:
    from .cli.cmd import main as kubectl_main
    return kubectl_main(argv)


def run_migrate_storage(argv: List[str]) -> int:
    """Rewrite every stored object through the current codec against a
    live apiserver (ref: hack/test-update-storage-objects.sh — the
    kubectl get | kubectl replace loop; kubernetes_tpu serves one wire
    version, so this normalizes legacy/unknown fields rather than
    converting between versions — core/migrate.py)."""
    import json as _json

    p = argparse.ArgumentParser(prog="migrate-storage")
    p.add_argument("--master", required=True)
    p.add_argument("--resources", default="",
                   help="comma-separated subset (default: everything)")
    args = p.parse_args(argv)

    from .api.client import HttpClient
    from .core.migrate import migrate_via_api

    _wait_for_master(args.master)
    resources = [r for r in args.resources.split(",") if r] or None
    report = migrate_via_api(HttpClient(args.master), resources)
    print(_json.dumps(report.as_dict()))
    return 1 if report.failed else 0


COMPONENTS = {
    "apiserver": run_apiserver,
    "kube-apiserver": run_apiserver,
    "scheduler": run_scheduler,
    "kube-scheduler": run_scheduler,
    "controller-manager": run_controller_manager,
    "kube-controller-manager": run_controller_manager,
    "kubelet": run_kubelet,
    "hollow-node": run_hollow_node,
    "hollow-fleet": run_hollow_fleet,
    "proxy": run_proxy,
    "kube-proxy": run_proxy,
    "kubectl": run_kubectl,
    "migrate-storage": run_migrate_storage,
    "dns": run_dns,
    "kube-dns": run_dns,
}


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        names = " | ".join(sorted(set(COMPONENTS)))
        print(f"usage: python -m kubernetes_tpu <{names}> [flags]")
        return 0 if argv else 1
    name = argv[0]
    if name not in COMPONENTS:
        print(f"unknown component {name!r}", file=sys.stderr)
        return 1
    return COMPONENTS[name](argv[1:])
