"""Compile-on-first-use for the native pieces (kvstore, pause).

One implementation of the build-and-cache-next-to-source pattern so the
error-handling contract cannot drift between call sites: stale outputs
rebuild (source newer than artifact), concurrent builders compile to
per-process temp names and install atomically, any failure — missing
toolchain, unwritable directory, compile error — degrades to None (the
caller picks its fallback), and a prebuilt artifact with no shipped
source is used as-is.
"""

from __future__ import annotations

import os
import subprocess
import tempfile
import threading
from typing import List, Optional, Sequence

_lock = threading.Lock()


def build_native(src: str, out: str,
                 flag_sets: Sequence[List[str]]) -> Optional[str]:
    """-> `out` when a usable artifact exists (built now or before),
    else None. flag_sets are tried in order (e.g. -static first)."""
    with _lock:
        have = os.path.exists(out)
        try:
            if have and (not os.path.exists(src)
                         or os.path.getmtime(src) <= os.path.getmtime(out)):
                return out
            if not os.path.exists(src):
                return None
            fd, tmp = tempfile.mkstemp(
                prefix=os.path.basename(out) + "-",
                dir=os.path.dirname(out))
            os.close(fd)
        except OSError:
            return out if have else None
        try:
            for flags in flag_sets:
                try:
                    subprocess.run([*flags, src, "-o", tmp],
                                   check=True, capture_output=True)
                    os.replace(tmp, out)
                    return out
                except (OSError, subprocess.CalledProcessError):
                    continue
            return out if have else None
        finally:
            try:
                if os.path.exists(tmp):
                    os.unlink(tmp)
            except OSError:
                pass
