"""Compile-on-first-use for the native pieces (kvstore, pause).

One implementation of the build-and-cache-next-to-source pattern so the
error-handling contract cannot drift between call sites: stale outputs
rebuild (source CONTENT changed since the artifact was built — tracked
through a hash sidecar, because mtime comparison silently serves a
stale artifact when an edit lands within the same second as the last
build), concurrent builders compile to per-process temp names and
install atomically, any failure — missing toolchain, unwritable
directory, compile error — degrades to None (the caller picks its
fallback), and a prebuilt artifact with no shipped source is used
as-is.
"""

from __future__ import annotations

import hashlib
import os
import subprocess
import tempfile
import threading
from typing import List, Optional, Sequence

_lock = threading.Lock()


def _sidecar(out: str) -> str:
    return out + ".src.sha256"


def _src_digest(src: str) -> Optional[str]:
    try:
        with open(src, "rb") as f:
            return hashlib.sha256(f.read()).hexdigest()
    except OSError:
        return None


def _fresh(src: str, out: str, digest: Optional[str]) -> bool:
    """Is the cached artifact current for this source? Content hash
    against the sidecar when possible; when hashing fails (unreadable
    source) fall back to STRICT mtime `<` — equal timestamps rebuild,
    the direction that can only waste a compile, never serve stale."""
    if digest is not None:
        try:
            with open(_sidecar(out)) as f:
                return f.read().strip() == digest
        except OSError:
            return False  # no sidecar: unknown provenance, rebuild
    try:
        return os.path.getmtime(src) < os.path.getmtime(out)
    except OSError:
        return False


def build_native(src: str, out: str,
                 flag_sets: Sequence[List[str]]) -> Optional[str]:
    """-> `out` when a usable artifact exists (built now or before),
    else None. flag_sets are tried in order (e.g. -static first)."""
    with _lock:
        have = os.path.exists(out)
        try:
            if have and not os.path.exists(src):
                return out  # prebuilt artifact, no shipped source
            digest = _src_digest(src) if os.path.exists(src) else None
            if have and os.path.exists(src) and _fresh(src, out, digest):
                return out
            if not os.path.exists(src):
                return None
            fd, tmp = tempfile.mkstemp(
                prefix=os.path.basename(out) + "-",
                dir=os.path.dirname(out))
            os.close(fd)
        except OSError:
            return out if have else None
        try:
            for flags in flag_sets:
                try:
                    subprocess.run([*flags, src, "-o", tmp],
                                   check=True, capture_output=True)
                    os.replace(tmp, out)
                    if digest is not None:
                        try:
                            with open(_sidecar(out), "w") as f:
                                f.write(digest + "\n")
                        except OSError:
                            pass  # sidecar is advisory; mtime fallback
                    return out
                except (OSError, subprocess.CalledProcessError):
                    continue
            return out if have else None
        finally:
            try:
                if os.path.exists(tmp):
                    os.unlink(tmp)
            except OSError:
                pass
