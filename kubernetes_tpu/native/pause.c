/* The pause container, C edition.
 *
 * Reference: third_party/pause/pause.asm — a 2-syscall x86-64 program
 * (pause(), then exit(0)) whose only job is to exist: it holds the
 * pod's namespaces open while real containers come and go. The
 * subprocess runtime spawns this for image-less containers (its
 * "default command"), giving every such pod a real native init process
 * instead of a shell sleep.
 *
 * Semantics matched to the reference: block until any terminating
 * signal arrives, then exit 0. (The reference's bare `pause` syscall
 * returns on ANY handled signal; we park in a loop so stray SIGCHLD &
 * co. don't end the pod, and exit cleanly on the kill the kubelet
 * sends.)
 */

#include <signal.h>
#include <unistd.h>

static volatile sig_atomic_t done = 0;

static void on_term(int sig) {
    (void)sig;
    done = 1;
}

int main(void) {
    struct sigaction sa = {0};
    sa.sa_handler = on_term;
    sigaction(SIGINT, &sa, 0);
    sigaction(SIGTERM, &sa, 0);
    while (!done) {
        pause();
    }
    return 0;
}
