// Native revisioned KV store with CAS and a windowed watch history.
//
// This is the framework's etcd: where the reference runs etcd as an external
// native (Go) process speaking CompareAndSwap + watch
// (pkg/storage/etcd/etcd_helper.go), this library provides the same
// semantics in-process behind a C ABI consumed via ctypes
// (core/native_store.py). The contract mirrors core/store.py exactly:
// monotonic revision counter doubling as resourceVersion, CAS on update and
// delete, lazy TTL expiry emitting DELETED events, an all-or-nothing batch
// commit, and a bounded event history with an oldest-replayable revision
// (the watch-cache window, pkg/storage/cacher.go:109).
//
// Build: g++ -O2 -std=c++17 -shared -fPIC kvstore.cc -o libkvstore.so
//
// Error codes (negative returns): -1 not found, -2 already exists,
// -3 conflict, -4 buffer too small (get only; list/events return the
// negative REQUIRED size so the caller allocates exactly once), -5 expired
// (watch window no longer covers since_rev), -6 revision window raced
// (kv_commit_txn only: another writer claimed the pre-assigned window —
// restage and retry; distinct from -3 so a CAS failure stays a real
// Conflict). Buffer-too-small results from list/events below -6 are
// distinguished by magnitude (sizes > 6).

#include <fcntl.h>
#include <unistd.h>

#include <algorithm>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <set>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr int64_t ERR_NOT_FOUND = -1;
constexpr int64_t ERR_EXISTS = -2;
constexpr int64_t ERR_CONFLICT = -3;
constexpr int64_t ERR_TOO_SMALL = -4;
constexpr int64_t ERR_EXPIRED = -5;
constexpr int64_t ERR_RACED = -6;
// Buffer-too-small size hints are returned as -(size + SIZE_HINT_BASE) so
// they occupy a range disjoint from the error codes above — a tiny payload
// (e.g. 4 bytes) must not alias ERR_TOO_SMALL. Callers recover the
// required size as (-ret) - SIZE_HINT_BASE.
constexpr int64_t SIZE_HINT_BASE = 64;

double now_seconds() {
  return std::chrono::duration<double>(
             std::chrono::system_clock::now().time_since_epoch())
      .count();
}

double mono_seconds() {
  return std::chrono::duration<double>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

uint64_t mono_ns() {
  return static_cast<uint64_t>(
      std::chrono::duration_cast<std::chrono::nanoseconds>(
          std::chrono::steady_clock::now().time_since_epoch())
          .count());
}

// CRC-32/IEEE (reflected, poly 0xEDB88320, init/xorout 0xFFFFFFFF) —
// bit-identical to Python's zlib.crc32, which is what core/wal.py
// stamps into every frame. The WAL parity contract depends on it.
struct Crc32Table {
  uint32_t t[256];
  Crc32Table() {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k)
        c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : (c >> 1);
      t[i] = c;
    }
  }
};

uint32_t crc32_ieee(const uint8_t* p, size_t n) {
  static const Crc32Table tbl;
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < n; ++i) c = tbl.t[(c ^ p[i]) & 0xFFu] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

// JSON string literal for a store key (ensure_ascii semantics like
// json.dumps; keys are ASCII registry paths, but escape defensively).
std::string json_quote(const std::string& s) {
  std::string out;
  out.reserve(s.size() + 2);
  out.push_back('"');
  for (unsigned char ch : s) {
    if (ch == '"' || ch == '\\') {
      out.push_back('\\');
      out.push_back(static_cast<char>(ch));
    } else if (ch < 0x20 || ch >= 0x7F) {
      char buf[8];
      std::snprintf(buf, sizeof(buf), "\\u%04x", ch);
      out += buf;
    } else {
      out.push_back(static_cast<char>(ch));
    }
  }
  out.push_back('"');
  return out;
}

bool write_all(int fd, const uint8_t* p, size_t n) {
  while (n > 0) {
    ssize_t w = ::write(fd, p, n);
    if (w <= 0) return false;
    p += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

struct Entry {
  std::string value;
  uint64_t mod_rev = 0;
  double expiry = 0;  // 0 = no TTL
};

enum class EventType : uint8_t { Added = 0, Modified = 1, Deleted = 2 };

struct Event {
  uint64_t rev;       // revision at which the event happened
  EventType type;
  std::string key;
  uint64_t obj_rev;   // resourceVersion to stamp on the delivered object
  std::string value;
};

struct Store {
  std::mutex mu;
  std::condition_variable cv;
  uint64_t rev = 0;
  uint64_t oldest_rev = 0;  // history no longer replays revs <= this... see emit
  size_t window;
  double next_expiry = 0;   // soonest pending TTL deadline; 0 = none
  std::map<std::string, Entry> data;  // ordered: list output is sorted
  std::deque<Event> history;

  // ---- native publish ring (kv_publish_start): in ring mode every
  // committed event batch is enqueued here under the ledger mutex and
  // a dedicated native publisher thread drains it into `history` —
  // the watch-visible ledger — advancing published_rev in strict
  // revision order. kv_wait then parks on published_rev, so watchers
  // never observe a committed-but-unpublished revision (the same
  // two-phase split core/store.py runs through _pub_queue, minus the
  // GIL). Window accounting (oldest_rev) stays LEDGER-time so the
  // Expired contract matches the Python store exactly.
  bool ring_mode = false;
  bool stopping = false;
  std::deque<std::vector<Event>> ring;
  std::condition_variable ring_cv;
  std::thread publisher;
  uint64_t published_rev = 0;

  // ---- native WAL appender (kv_wal_attach): frames caller-built
  // payloads with <u32 len><u32 crc32> and appends them to
  // wal-%020d.seg segments, mirroring core/wal.py WalWriter byte for
  // byte (lazy segment open named by the first record's revision,
  // rotation by logical record count, fsync always/batched@50ms).
  bool wal_attached = false;
  std::string wal_dir;
  int wal_fd = -1;
  bool wal_fsync_always = false;
  uint64_t wal_seg_limit = 10000;
  uint64_t wal_seg_count = 0;
  double wal_last_fsync = 0.0;

  // ---- engine counters (kv_stats): the ledger/publish split the
  // profile tooling reads, since a sampler can't see native threads.
  uint64_t commits = 0;
  uint64_t ledger_ns = 0;
  uint64_t published_batches = 0;
  uint64_t publish_ns = 0;
  uint64_t wal_frames = 0;
  uint64_t wal_bytes = 0;

  explicit Store(size_t window_size) : window(window_size) {}

  uint64_t bump() { return ++rev; }

  void push_history(Event&& e) {
    if (history.size() == window) {
      if (history.front().rev > oldest_rev)
        oldest_rev = history.front().rev;
      history.pop_front();
    }
    history.push_back(std::move(e));
  }

  // Ledger-time window accounting for ring mode: revisions map 1:1
  // onto events, so once r outruns the window the oldest replayable
  // revision is r - window regardless of how far the publisher lags —
  // exactly the commit-time _oldest_rev bump the Python store does.
  void roll_window(uint64_t r) {
    if (r > window && r - window > oldest_rev) oldest_rev = r - window;
  }

  void publish(std::vector<Event>&& batch) {
    if (batch.empty()) return;
    if (ring_mode && !stopping) {
      roll_window(batch.back().rev);
      ring.push_back(std::move(batch));
      ring_cv.notify_one();
    } else {
      for (auto& e : batch) push_history(std::move(e));
      cv.notify_all();
    }
  }

  void emit(uint64_t r, EventType t, const std::string& key,
            uint64_t obj_rev, const std::string& value) {
    std::vector<Event> one;
    one.push_back(Event{r, t, key, obj_rev, value});
    publish(std::move(one));
  }

  bool expired(const Entry& e, double now) const {
    return e.expiry != 0 && e.expiry <= now;
  }

  void note_expiry(double expiry) {
    if (expiry != 0 && (next_expiry == 0 || expiry < next_expiry))
      next_expiry = expiry;
  }

  bool wal_write_frame(const uint8_t* payload, uint64_t len,
                       uint64_t name_rev) {
    if (wal_fd < 0) {
      char name[48];
      std::snprintf(name, sizeof(name), "wal-%020llu.seg",
                    static_cast<unsigned long long>(name_rev));
      std::string path = wal_dir + "/" + name;
      wal_fd = ::open(path.c_str(), O_WRONLY | O_CREAT | O_APPEND, 0644);
      if (wal_fd < 0) return false;
    }
    uint8_t hdr[8];
    uint32_t l = static_cast<uint32_t>(len);
    uint32_t c = crc32_ieee(payload, len);
    std::memcpy(hdr, &l, 4);
    std::memcpy(hdr + 4, &c, 4);
    if (!write_all(wal_fd, hdr, 8)) return false;
    if (!write_all(wal_fd, payload, len)) return false;
    wal_frames++;
    wal_bytes += 8 + len;
    return true;
  }

  // Post-commit WAL bookkeeping, one call per ledger window: fsync
  // per policy (always, or batched at WalWriter's 50ms cadence) and
  // rotate once the segment holds segment_records LOGICAL records —
  // the same rotate-after-commit rule WalWriter.commit applies, so
  // the same record stream lands in identically-named, byte-identical
  // segment files.
  void wal_commit_done(uint64_t n_records) {
    if (wal_fd < 0) return;
    wal_seg_count += n_records;
    double now = mono_seconds();
    if (wal_fsync_always || now - wal_last_fsync >= 0.05) {
      ::fsync(wal_fd);
      wal_last_fsync = now;
    }
    if (wal_seg_limit != 0 && wal_seg_count >= wal_seg_limit) {
      ::fsync(wal_fd);
      ::close(wal_fd);
      wal_fd = -1;
      wal_seg_count = 0;
    }
  }

  void wal_close_locked() {
    if (wal_fd >= 0) {
      ::fsync(wal_fd);
      ::close(wal_fd);
      wal_fd = -1;
    }
  }

  // TTL GC, mirroring core/store.py _gc_expired: expired entries are
  // deleted and emit DELETED carrying the stale object. Runs on reads
  // too (first-class expiry); the next_expiry guard keeps the no-due
  // common case O(1) instead of a full-map scan per call. With a WAL
  // attached the expiry deletions journal too (composed natively from
  // the stored wire bytes) — skipping them would tear revision
  // contiguity and fail recovery on the next journaled record.
  void gc(double now) {
    if (next_expiry == 0 || next_expiry > now) return;
    std::vector<std::string> dead;
    double nxt = 0;
    for (auto& [k, e] : data) {
      if (expired(e, now)) {
        dead.push_back(k);
      } else if (e.expiry != 0 && (nxt == 0 || e.expiry < nxt)) {
        nxt = e.expiry;
      }
    }
    next_expiry = nxt;
    for (auto& k : dead) {
      Entry e = data[k];
      data.erase(k);
      uint64_t r = bump();
      if (wal_attached) {
        std::string payload = "[" + std::to_string(r) + ",\"DELETED\"," +
                              json_quote(k) + ",null," + e.value + "]";
        wal_write_frame(reinterpret_cast<const uint8_t*>(payload.data()),
                        payload.size(), r);
        wal_commit_done(1);
      }
      emit(r, EventType::Deleted, k, e.mod_rev, e.value);
    }
  }
};

// Drains the publish ring into the watch-visible history, off the
// GIL: a pure native thread, so fan-out wakeups and history rolls
// cost zero interpreter time while the device executes the next tile.
void publisher_main(Store* s) {
  std::unique_lock<std::mutex> lk(s->mu);
  for (;;) {
    s->ring_cv.wait(lk, [&] { return s->stopping || !s->ring.empty(); });
    if (s->ring.empty()) {
      if (s->stopping) return;  // drained AND told to stop
      continue;
    }
    std::vector<Event> batch = std::move(s->ring.front());
    s->ring.pop_front();
    uint64_t t0 = mono_ns();
    uint64_t last = batch.back().rev;
    for (auto& e : batch) s->push_history(std::move(e));
    s->published_rev = last;
    s->published_batches++;
    s->publish_ns += mono_ns() - t0;
    s->cv.notify_all();
  }
}

// Serialize records into caller buffers.
// Event record:  u64 rev | u8 type | u32 klen | key | u64 obj_rev |
//                u32 vlen | value
// List record:   u64 obj_rev | u32 klen | key | u32 vlen | value
class Writer {
 public:
  Writer(uint8_t* buf, int64_t cap) : buf_(buf), cap_(cap) {}

  template <typename T>
  void put(T v) {
    if (pos_ + static_cast<int64_t>(sizeof(T)) <= cap_ && buf_) {
      std::memcpy(buf_ + pos_, &v, sizeof(T));
    }
    pos_ += sizeof(T);
  }

  void put_bytes(const std::string& s) {
    put<uint32_t>(static_cast<uint32_t>(s.size()));
    if (pos_ + static_cast<int64_t>(s.size()) <= cap_ && buf_) {
      std::memcpy(buf_ + pos_, s.data(), s.size());
    }
    pos_ += s.size();
  }

  bool fits() const { return pos_ <= cap_; }
  int64_t size() const { return pos_; }

 private:
  uint8_t* buf_;
  int64_t cap_;
  int64_t pos_ = 0;
};

}  // namespace

extern "C" {

void* kv_open(uint64_t window) { return new Store(window); }

// Stop the publisher (draining the ring first), wake every kv_wait
// parked thread, and seal the WAL. Idempotent; kv_close implies it.
// This is what lets NativeStore.close() behave like a process kill:
// watcher threads blocked in kv_wait return immediately instead of
// riding out their poll timeout.
void kv_shutdown(void* h) {
  Store* s = static_cast<Store*>(h);
  {
    std::lock_guard<std::mutex> lk(s->mu);
    s->stopping = true;
    s->ring_cv.notify_all();
    s->cv.notify_all();
  }
  if (s->publisher.joinable()) s->publisher.join();
  std::lock_guard<std::mutex> lk(s->mu);
  s->wal_close_locked();
}

void kv_close(void* h) {
  Store* s = static_cast<Store*>(h);
  kv_shutdown(h);
  delete s;
}

uint64_t kv_current_rev(void* h) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  return s->rev;
}

uint64_t kv_oldest_rev(void* h) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  return s->oldest_rev;
}

int64_t kv_create(void* h, const char* key, const uint8_t* val,
                  uint64_t val_len, double ttl_seconds) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  double now = now_seconds();
  s->gc(now);
  std::string k(key);
  if (s->data.count(k)) return ERR_EXISTS;
  uint64_t rev = s->bump();
  Entry e{std::string(reinterpret_cast<const char*>(val), val_len), rev,
          ttl_seconds > 0 ? now + ttl_seconds : 0};
  s->note_expiry(e.expiry);
  s->data[k] = e;
  s->emit(rev, EventType::Added, k, rev, e.value);
  return static_cast<int64_t>(rev);
}

int64_t kv_set(void* h, const char* key, const uint8_t* val,
               uint64_t val_len, double ttl_seconds) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  double now = now_seconds();
  s->gc(now);
  std::string k(key);
  bool existed = s->data.count(k) > 0;
  uint64_t rev = s->bump();
  Entry e{std::string(reinterpret_cast<const char*>(val), val_len), rev,
          ttl_seconds > 0 ? now + ttl_seconds : 0};
  s->note_expiry(e.expiry);
  s->data[k] = e;
  s->emit(rev, existed ? EventType::Modified : EventType::Added, k, rev,
          e.value);
  return static_cast<int64_t>(rev);
}

// expect_rev 0 = unconditional (but the key must exist).
int64_t kv_update(void* h, const char* key, const uint8_t* val,
                  uint64_t val_len, uint64_t expect_rev) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  s->gc(now_seconds());
  std::string k(key);
  auto it = s->data.find(k);
  if (it == s->data.end()) return ERR_NOT_FOUND;
  if (expect_rev != 0 && it->second.mod_rev != expect_rev)
    return ERR_CONFLICT;
  uint64_t rev = s->bump();
  it->second.value.assign(reinterpret_cast<const char*>(val), val_len);
  it->second.mod_rev = rev;  // TTL carries over, like core/store.py update
  s->emit(rev, EventType::Modified, k, rev, it->second.value);
  return static_cast<int64_t>(rev);
}

int64_t kv_delete(void* h, const char* key, uint64_t expect_rev) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  s->gc(now_seconds());
  std::string k(key);
  auto it = s->data.find(k);
  if (it == s->data.end()) return ERR_NOT_FOUND;
  if (expect_rev != 0 && it->second.mod_rev != expect_rev)
    return ERR_CONFLICT;
  Entry e = it->second;
  s->data.erase(it);
  uint64_t rev = s->bump();
  s->emit(rev, EventType::Deleted, k, e.mod_rev, e.value);
  return static_cast<int64_t>(rev);
}

int64_t kv_get(void* h, const char* key, uint8_t* buf, int64_t buflen,
               uint64_t* mod_rev) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  // first-class TTL expiry (mirrors core/store.py get/list): a read
  // past a due deadline COMMITS the deletion to the ledger rather than
  // skipping passively, so history and recovery agree on when the key
  // died; the next_expiry guard keeps the no-due case O(1).
  s->gc(now_seconds());
  std::string k(key);
  auto it = s->data.find(k);
  if (it == s->data.end()) return ERR_NOT_FOUND;
  const std::string& v = it->second.value;
  *mod_rev = it->second.mod_rev;
  if (static_cast<int64_t>(v.size()) > buflen) return ERR_TOO_SMALL;
  std::memcpy(buf, v.data(), v.size());
  return static_cast<int64_t>(v.size());
}

// Buffer layout: u64 store_rev | u32 count | records...
int64_t kv_list(void* h, const char* prefix, uint8_t* buf, int64_t buflen) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  double now = now_seconds();
  s->gc(now);  // first-class expiry, same contract as kv_get
  std::string p(prefix);
  Writer w(buf, buflen);
  w.put<uint64_t>(s->rev);
  uint32_t count = 0;
  Writer counter(nullptr, 0);  // first pass to count
  for (auto it = s->data.lower_bound(p); it != s->data.end(); ++it) {
    if (it->first.compare(0, p.size(), p) != 0) break;
    if (s->expired(it->second, now)) continue;
    ++count;
  }
  w.put<uint32_t>(count);
  for (auto it = s->data.lower_bound(p); it != s->data.end(); ++it) {
    if (it->first.compare(0, p.size(), p) != 0) break;
    if (s->expired(it->second, now)) continue;
    w.put<uint64_t>(it->second.mod_rev);
    w.put_bytes(it->first);
    w.put_bytes(it->second.value);
  }
  if (!w.fits()) return -(w.size() + SIZE_HINT_BASE);  // size hint: grow + retry
  return w.size();
}

// All-or-nothing multi-key CAS commit (the binding tile fast path,
// core/store.py batch). expect_revs[i] 0 = no per-key CAS check.
int64_t kv_batch(void* h, uint64_t n, const char** keys,
                 const uint8_t** vals, const uint64_t* val_lens,
                 const uint64_t* expect_revs) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  s->gc(now_seconds());
  // validate everything first: a mid-batch failure commits nothing
  for (uint64_t i = 0; i < n; ++i) {
    auto it = s->data.find(keys[i]);
    if (it == s->data.end()) return ERR_NOT_FOUND;
    if (expect_revs[i] != 0 && it->second.mod_rev != expect_revs[i])
      return ERR_CONFLICT;
  }
  int64_t first_rev = 0;
  for (uint64_t i = 0; i < n; ++i) {
    auto it = s->data.find(keys[i]);
    uint64_t rev = s->bump();
    if (first_rev == 0) first_rev = static_cast<int64_t>(rev);
    it->second.value.assign(reinterpret_cast<const char*>(vals[i]),
                            val_lens[i]);
    it->second.mod_rev = rev;
    s->emit(rev, EventType::Modified, it->first, rev, it->second.value);
  }
  return first_rev;
}

// Batched create: every key must be absent (including duplicates
// WITHIN the batch) or nothing commits — the write-side analogue of
// kv_batch. Returns the first assigned revision, or ERR_EXISTS.
int64_t kv_create_batch(void* h, uint64_t n, const char** keys,
                        const uint8_t** vals, const uint64_t* val_lens,
                        const double* ttls) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  double now = now_seconds();
  s->gc(now);
  std::set<std::string> seen;
  for (uint64_t i = 0; i < n; ++i) {
    std::string k(keys[i]);
    if (s->data.count(k) || !seen.insert(k).second) return ERR_EXISTS;
  }
  int64_t first_rev = 0;
  for (uint64_t i = 0; i < n; ++i) {
    std::string k(keys[i]);
    uint64_t rev = s->bump();
    if (first_rev == 0) first_rev = static_cast<int64_t>(rev);
    Entry e{std::string(reinterpret_cast<const char*>(vals[i]),
                        val_lens[i]),
            rev, ttls[i] > 0 ? now + ttls[i] : 0};
    s->note_expiry(e.expiry);
    s->data[k] = e;
    s->emit(rev, EventType::Added, k, rev, e.value);
  }
  return first_rev;
}

// Events with rev > since_rev for keys under prefix.
// Layout: u32 count | event records... Returns bytes used, or
// -(required + SIZE_HINT_BASE) if the buffer is too small, or ERR_EXPIRED.
int64_t kv_events(void* h, uint64_t since_rev, const char* prefix,
                  uint8_t* buf, int64_t buflen) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  if (since_rev < s->oldest_rev) return ERR_EXPIRED;
  std::string p(prefix);
  Writer w(buf, buflen);
  // history is revision-ordered: binary-search the resume point so a
  // watcher poll costs O(log n + new events), not a full window scan
  auto begin = std::upper_bound(
      s->history.begin(), s->history.end(), since_rev,
      [](uint64_t rev, const Event& e) { return rev < e.rev; });
  uint32_t count = 0;
  for (auto it = begin; it != s->history.end(); ++it) {
    if (it->key.compare(0, p.size(), p) == 0) ++count;
  }
  w.put<uint32_t>(count);
  for (auto it = begin; it != s->history.end(); ++it) {
    const Event& e = *it;
    if (e.key.compare(0, p.size(), p) != 0) continue;
    w.put<uint64_t>(e.rev);
    w.put<uint8_t>(static_cast<uint8_t>(e.type));
    w.put_bytes(e.key);
    w.put<uint64_t>(e.obj_rev);
    w.put_bytes(e.value);
  }
  if (!w.fits()) return -(w.size() + SIZE_HINT_BASE);
  return w.size();
}

// ---------------------------------------------------------- recovery
// WAL recovery entry points (core/wal.py + NativeStore.recover): the
// Python side reads the snapshot + record tail and replays it here.

// Insert one snapshot entry with its original mod_rev and absolute
// expiry, emitting NO history event (snapshot state predates the
// replayable window). Advances the revision counter monotonically.
int64_t kv_restore(void* h, const char* key, const uint8_t* val,
                   uint64_t val_len, uint64_t mod_rev, double expiry) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  Entry e{std::string(reinterpret_cast<const char*>(val), val_len),
          mod_rev, expiry};
  s->note_expiry(expiry);
  s->data[std::string(key)] = e;
  if (mod_rev > s->rev) s->rev = mod_rev;
  return static_cast<int64_t>(mod_rev);
}

// Seal the snapshot restore point: revisions <= rev are not
// replayable from history (the watch-window meaning of oldest_rev).
void kv_restore_seal(void* h, uint64_t rev) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  if (rev > s->rev) s->rev = rev;
  s->oldest_rev = rev;
}

// Replay one ledger record at EXACTLY the given revision (the WAL
// tail). Unlike the write verbs, no gc runs and no revision is
// assigned here — the record's revision is authoritative, so replay
// reproduces the pre-crash ledger prefix bit-identically. obj_rev is
// the resourceVersion the delivered event stamps (pre-delete mod_rev
// for DELETED records).
int64_t kv_replay(void* h, uint64_t rev, uint8_t type, const char* key,
                  const uint8_t* val, uint64_t val_len, uint64_t obj_rev,
                  double expiry) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  if (rev <= s->rev) return ERR_CONFLICT;
  s->rev = rev;
  std::string k(key);
  std::string v(reinterpret_cast<const char*>(val), val_len);
  if (type == static_cast<uint8_t>(EventType::Deleted)) {
    s->data.erase(k);
    s->emit(rev, EventType::Deleted, k, obj_rev, v);
  } else {
    Entry e{v, rev, expiry};
    s->note_expiry(expiry);
    s->data[k] = e;
    s->emit(rev, static_cast<EventType>(type), k, rev, v);
  }
  return static_cast<int64_t>(rev);
}

// Replay one TXN frame's whole window (core/wal.py TXN records) in
// ONE lock window: the frame was one CRC unit on disk, so it recovers
// as one atomic unit in the engine too — mirroring kv_batch's commit
// shape. Revisions must be consecutive and start strictly after the
// current revision; per-record semantics are exactly kv_replay's.
// Returns the last replayed revision, or ERR_CONFLICT.
int64_t kv_replay_txn(void* h, uint64_t n, const uint64_t* revs,
                      const uint8_t* types, const char** keys,
                      const uint8_t** vals, const uint64_t* val_lens,
                      const uint64_t* obj_revs, const double* expiries) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  if (n == 0) return static_cast<int64_t>(s->rev);
  if (revs[0] <= s->rev) return ERR_CONFLICT;
  for (uint64_t i = 1; i < n; ++i)
    if (revs[i] != revs[0] + i) return ERR_CONFLICT;
  for (uint64_t i = 0; i < n; ++i) {
    uint64_t rev = revs[i];
    s->rev = rev;
    std::string k(keys[i]);
    std::string v(reinterpret_cast<const char*>(vals[i]), val_lens[i]);
    if (types[i] == static_cast<uint8_t>(EventType::Deleted)) {
      s->data.erase(k);
      s->emit(rev, EventType::Deleted, k, obj_revs[i], v);
    } else {
      Entry e{v, rev, expiries[i]};
      s->note_expiry(expiries[i]);
      s->data[k] = e;
      s->emit(rev, static_cast<EventType>(types[i]), k, rev, v);
    }
  }
  return static_cast<int64_t>(s->rev);
}

// Block until the watch-visible revision exceeds since_rev (or
// timeout, or shutdown). In ring mode that is published_rev — history
// only ever holds published events, so waking on the ledger revision
// would busy-spin watchers against not-yet-drained commits. Returns
// the watch-visible revision. ctypes releases the GIL around this, so
// watcher threads park in native code, not in Python polling loops.
uint64_t kv_wait(void* h, uint64_t since_rev, double timeout_seconds) {
  Store* s = static_cast<Store*>(h);
  std::unique_lock<std::mutex> lk(s->mu);
  s->cv.wait_for(
      lk, std::chrono::duration<double>(timeout_seconds), [&] {
        return s->stopping ||
               (s->ring_mode ? s->published_rev : s->rev) > since_rev;
      });
  return s->ring_mode ? s->published_rev : s->rev;
}

// ------------------------------------------- native commit path (ISSUE 17)

// Flip the store into ring mode and start the native publisher.
// Idempotent. From here on every committed event batch is published
// by the native thread, in enqueue (= revision) order.
int64_t kv_publish_start(void* h) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  if (s->ring_mode) return 0;
  if (s->stopping) return ERR_CONFLICT;
  s->ring_mode = true;
  s->published_rev = s->rev;
  s->publisher = std::thread(publisher_main, s);
  return 0;
}

// Wait until the publisher has caught up with the ledger (or timeout/
// shutdown). Returns the watch-visible revision. The committer's
// drain barrier uses this so "drained" keeps meaning "visible to
// watchers" on the native path, matching Store._drain_publish.
uint64_t kv_publish_flush(void* h, double timeout_seconds) {
  Store* s = static_cast<Store*>(h);
  std::unique_lock<std::mutex> lk(s->mu);
  s->cv.wait_for(lk, std::chrono::duration<double>(timeout_seconds), [&] {
    return s->stopping || !s->ring_mode || s->published_rev >= s->rev;
  });
  return s->ring_mode ? s->published_rev : s->rev;
}

// Attach the native WAL appender. The directory must exist (the
// Python side creates it); fsync_always != 0 = fsync every commit,
// else WalWriter's 50ms batch cadence. segment_records mirrors
// WalWriter: rotate after that many LOGICAL records (0 = never).
int64_t kv_wal_attach(void* h, const char* dir, int fsync_always,
                      uint64_t segment_records) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  s->wal_dir = dir;
  s->wal_fsync_always = fsync_always != 0;
  s->wal_seg_limit = segment_records;
  s->wal_attached = true;
  return 0;
}

// kv_get plus the entry's absolute TTL deadline (0 = none) — the
// commit staging path needs it to carry expiry into WAL records the
// way Store.commit_txn journals the preserved entry expiry.
int64_t kv_get_ex(void* h, const char* key, uint8_t* buf, int64_t buflen,
                  uint64_t* mod_rev, double* expiry) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  s->gc(now_seconds());
  std::string k(key);
  auto it = s->data.find(k);
  if (it == s->data.end()) return ERR_NOT_FOUND;
  const std::string& v = it->second.value;
  *mod_rev = it->second.mod_rev;
  *expiry = it->second.expiry;
  if (static_cast<int64_t>(v.size()) > buflen) return ERR_TOO_SMALL;
  std::memcpy(buf, v.data(), v.size());
  return static_cast<int64_t>(v.size());
}

// Engine counters: [commits, ledger_ns, published_batches, publish_ns,
// wal_frames, wal_bytes, rev, published_rev]. The ledger/publish
// split a Python sampler cannot see (native threads have no frames).
void kv_stats(void* h, uint64_t* out) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  out[0] = s->commits;
  out[1] = s->ledger_ns;
  out[2] = s->published_batches;
  out[3] = s->publish_ns;
  out[4] = s->wal_frames;
  out[5] = s->wal_bytes;
  out[6] = s->rev;
  out[7] = s->ring_mode ? s->published_rev : s->rev;
}

// The native commit path: apply n records under ONE mutex window at a
// PRE-ASSIGNED revision window (first_rev .. first_rev+n-1), append
// the caller-built WAL payload(s) with native framing, and hand the
// ordered event batch to the publish ring. The caller stages
// optimistically (reads, runs update fns, stamps resourceVersions,
// builds payload bytes through core/wal.py's shared codec) and
// retries on ERR_RACED when another writer claimed the window —
// revisions inside values/payloads must match the ones assigned here,
// which is exactly what the window check guarantees.
//
// types[i]: 0 ADDED (key must be absent, also intra-batch), 1
// MODIFIED / 2 DELETED (key must exist; expect_revs[i] != 0 is a CAS
// on mod_rev). expiries[i] is an ABSOLUTE deadline (0 = none;
// MODIFIED carries the caller-read old expiry over, like kv_update).
// For DELETED, vals[i] is the pre-delete wire (the event value).
// Validation is all-or-nothing: nothing commits on any failure.
//
// frames: n_frames payloads to journal — one TXN payload for a
// transaction, or n flat record payloads (frame j names a fresh
// segment after revision first_rev + j, the WalWriter naming rule).
int64_t kv_commit_txn(void* h, uint64_t n, uint64_t first_rev,
                      const uint8_t* types, const char** keys,
                      const uint8_t** vals, const uint64_t* val_lens,
                      const uint64_t* expect_revs, const double* expiries,
                      uint64_t n_frames, const uint8_t** frames,
                      const uint64_t* frame_lens) {
  Store* s = static_cast<Store*>(h);
  uint64_t t0 = mono_ns();
  std::lock_guard<std::mutex> lk(s->mu);
  double now = now_seconds();
  s->gc(now);  // may bump revisions (and journal) — then the window check
  if (n == 0) return static_cast<int64_t>(s->rev);
  if (first_rev != s->rev + 1) return ERR_RACED;
  std::set<std::string> fresh;  // keys ADDED earlier in this batch
  for (uint64_t i = 0; i < n; ++i) {
    std::string k(keys[i]);
    bool exists = s->data.count(k) != 0 || fresh.count(k) != 0;
    if (types[i] == static_cast<uint8_t>(EventType::Added)) {
      if (exists) return ERR_EXISTS;
      fresh.insert(k);
    } else {
      if (!exists) return ERR_NOT_FOUND;
      if (expect_revs[i] != 0) {
        auto it = s->data.find(k);
        if (it == s->data.end() || it->second.mod_rev != expect_revs[i])
          return ERR_CONFLICT;
      }
    }
  }
  std::vector<Event> batch;
  batch.reserve(n);
  for (uint64_t i = 0; i < n; ++i) {
    uint64_t rev = s->bump();
    std::string k(keys[i]);
    std::string v(reinterpret_cast<const char*>(vals[i]), val_lens[i]);
    if (types[i] == static_cast<uint8_t>(EventType::Deleted)) {
      auto it = s->data.find(k);
      uint64_t obj_rev = (it != s->data.end()) ? it->second.mod_rev : rev;
      if (it != s->data.end()) s->data.erase(it);
      batch.push_back(Event{rev, EventType::Deleted, k, obj_rev, v});
    } else {
      Entry e{v, rev, expiries[i] > 0 ? expiries[i] : 0};
      s->note_expiry(e.expiry);
      s->data[k] = std::move(e);
      batch.push_back(Event{rev, static_cast<EventType>(types[i]), k, rev,
                            std::move(v)});
    }
  }
  if (s->wal_attached && n_frames > 0) {
    for (uint64_t j = 0; j < n_frames; ++j)
      s->wal_write_frame(frames[j], frame_lens[j], first_rev + j);
    s->wal_commit_done(n);
  }
  s->publish(std::move(batch));
  s->commits++;
  s->ledger_ns += mono_ns() - t0;
  return static_cast<int64_t>(first_rev);
}

}  // extern "C"
