// Native revisioned KV store with CAS and a windowed watch history.
//
// This is the framework's etcd: where the reference runs etcd as an external
// native (Go) process speaking CompareAndSwap + watch
// (pkg/storage/etcd/etcd_helper.go), this library provides the same
// semantics in-process behind a C ABI consumed via ctypes
// (core/native_store.py). The contract mirrors core/store.py exactly:
// monotonic revision counter doubling as resourceVersion, CAS on update and
// delete, lazy TTL expiry emitting DELETED events, an all-or-nothing batch
// commit, and a bounded event history with an oldest-replayable revision
// (the watch-cache window, pkg/storage/cacher.go:109).
//
// Build: g++ -O2 -std=c++17 -shared -fPIC kvstore.cc -o libkvstore.so
//
// Error codes (negative returns): -1 not found, -2 already exists,
// -3 conflict, -4 buffer too small (get only; list/events return the
// negative REQUIRED size so the caller allocates exactly once), -5 expired
// (watch window no longer covers since_rev). Buffer-too-small results from
// list/events below -5 are distinguished by magnitude (sizes > 5).

#include <algorithm>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <set>
#include <string>
#include <vector>

namespace {

constexpr int64_t ERR_NOT_FOUND = -1;
constexpr int64_t ERR_EXISTS = -2;
constexpr int64_t ERR_CONFLICT = -3;
constexpr int64_t ERR_TOO_SMALL = -4;
constexpr int64_t ERR_EXPIRED = -5;
// Buffer-too-small size hints are returned as -(size + SIZE_HINT_BASE) so
// they occupy a range disjoint from the error codes above — a tiny payload
// (e.g. 4 bytes) must not alias ERR_TOO_SMALL. Callers recover the
// required size as (-ret) - SIZE_HINT_BASE.
constexpr int64_t SIZE_HINT_BASE = 64;

double now_seconds() {
  return std::chrono::duration<double>(
             std::chrono::system_clock::now().time_since_epoch())
      .count();
}

struct Entry {
  std::string value;
  uint64_t mod_rev = 0;
  double expiry = 0;  // 0 = no TTL
};

enum class EventType : uint8_t { Added = 0, Modified = 1, Deleted = 2 };

struct Event {
  uint64_t rev;       // revision at which the event happened
  EventType type;
  std::string key;
  uint64_t obj_rev;   // resourceVersion to stamp on the delivered object
  std::string value;
};

struct Store {
  std::mutex mu;
  std::condition_variable cv;
  uint64_t rev = 0;
  uint64_t oldest_rev = 0;  // history no longer replays revs <= this... see emit
  size_t window;
  double next_expiry = 0;   // soonest pending TTL deadline; 0 = none
  std::map<std::string, Entry> data;  // ordered: list output is sorted
  std::deque<Event> history;

  explicit Store(size_t window_size) : window(window_size) {}

  uint64_t bump() { return ++rev; }

  void emit(uint64_t r, EventType t, const std::string& key,
            uint64_t obj_rev, const std::string& value) {
    if (history.size() == window) {
      oldest_rev = history.front().rev;
      history.pop_front();
    }
    history.push_back(Event{r, t, key, obj_rev, value});
    cv.notify_all();
  }

  bool expired(const Entry& e, double now) const {
    return e.expiry != 0 && e.expiry <= now;
  }

  void note_expiry(double expiry) {
    if (expiry != 0 && (next_expiry == 0 || expiry < next_expiry))
      next_expiry = expiry;
  }

  // TTL GC, mirroring core/store.py _gc_expired: expired entries are
  // deleted and emit DELETED carrying the stale object. Runs on reads
  // too (first-class expiry); the next_expiry guard keeps the no-due
  // common case O(1) instead of a full-map scan per call.
  void gc(double now) {
    if (next_expiry == 0 || next_expiry > now) return;
    std::vector<std::string> dead;
    double nxt = 0;
    for (auto& [k, e] : data) {
      if (expired(e, now)) {
        dead.push_back(k);
      } else if (e.expiry != 0 && (nxt == 0 || e.expiry < nxt)) {
        nxt = e.expiry;
      }
    }
    next_expiry = nxt;
    for (auto& k : dead) {
      Entry e = data[k];
      data.erase(k);
      emit(bump(), EventType::Deleted, k, e.mod_rev, e.value);
    }
  }
};

// Serialize records into caller buffers.
// Event record:  u64 rev | u8 type | u32 klen | key | u64 obj_rev |
//                u32 vlen | value
// List record:   u64 obj_rev | u32 klen | key | u32 vlen | value
class Writer {
 public:
  Writer(uint8_t* buf, int64_t cap) : buf_(buf), cap_(cap) {}

  template <typename T>
  void put(T v) {
    if (pos_ + static_cast<int64_t>(sizeof(T)) <= cap_ && buf_) {
      std::memcpy(buf_ + pos_, &v, sizeof(T));
    }
    pos_ += sizeof(T);
  }

  void put_bytes(const std::string& s) {
    put<uint32_t>(static_cast<uint32_t>(s.size()));
    if (pos_ + static_cast<int64_t>(s.size()) <= cap_ && buf_) {
      std::memcpy(buf_ + pos_, s.data(), s.size());
    }
    pos_ += s.size();
  }

  bool fits() const { return pos_ <= cap_; }
  int64_t size() const { return pos_; }

 private:
  uint8_t* buf_;
  int64_t cap_;
  int64_t pos_ = 0;
};

}  // namespace

extern "C" {

void* kv_open(uint64_t window) { return new Store(window); }

void kv_close(void* h) { delete static_cast<Store*>(h); }

uint64_t kv_current_rev(void* h) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  return s->rev;
}

uint64_t kv_oldest_rev(void* h) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  return s->oldest_rev;
}

int64_t kv_create(void* h, const char* key, const uint8_t* val,
                  uint64_t val_len, double ttl_seconds) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  double now = now_seconds();
  s->gc(now);
  std::string k(key);
  if (s->data.count(k)) return ERR_EXISTS;
  uint64_t rev = s->bump();
  Entry e{std::string(reinterpret_cast<const char*>(val), val_len), rev,
          ttl_seconds > 0 ? now + ttl_seconds : 0};
  s->note_expiry(e.expiry);
  s->data[k] = e;
  s->emit(rev, EventType::Added, k, rev, e.value);
  return static_cast<int64_t>(rev);
}

int64_t kv_set(void* h, const char* key, const uint8_t* val,
               uint64_t val_len, double ttl_seconds) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  double now = now_seconds();
  s->gc(now);
  std::string k(key);
  bool existed = s->data.count(k) > 0;
  uint64_t rev = s->bump();
  Entry e{std::string(reinterpret_cast<const char*>(val), val_len), rev,
          ttl_seconds > 0 ? now + ttl_seconds : 0};
  s->note_expiry(e.expiry);
  s->data[k] = e;
  s->emit(rev, existed ? EventType::Modified : EventType::Added, k, rev,
          e.value);
  return static_cast<int64_t>(rev);
}

// expect_rev 0 = unconditional (but the key must exist).
int64_t kv_update(void* h, const char* key, const uint8_t* val,
                  uint64_t val_len, uint64_t expect_rev) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  s->gc(now_seconds());
  std::string k(key);
  auto it = s->data.find(k);
  if (it == s->data.end()) return ERR_NOT_FOUND;
  if (expect_rev != 0 && it->second.mod_rev != expect_rev)
    return ERR_CONFLICT;
  uint64_t rev = s->bump();
  it->second.value.assign(reinterpret_cast<const char*>(val), val_len);
  it->second.mod_rev = rev;  // TTL carries over, like core/store.py update
  s->emit(rev, EventType::Modified, k, rev, it->second.value);
  return static_cast<int64_t>(rev);
}

int64_t kv_delete(void* h, const char* key, uint64_t expect_rev) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  s->gc(now_seconds());
  std::string k(key);
  auto it = s->data.find(k);
  if (it == s->data.end()) return ERR_NOT_FOUND;
  if (expect_rev != 0 && it->second.mod_rev != expect_rev)
    return ERR_CONFLICT;
  Entry e = it->second;
  s->data.erase(it);
  uint64_t rev = s->bump();
  s->emit(rev, EventType::Deleted, k, e.mod_rev, e.value);
  return static_cast<int64_t>(rev);
}

int64_t kv_get(void* h, const char* key, uint8_t* buf, int64_t buflen,
               uint64_t* mod_rev) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  // first-class TTL expiry (mirrors core/store.py get/list): a read
  // past a due deadline COMMITS the deletion to the ledger rather than
  // skipping passively, so history and recovery agree on when the key
  // died; the next_expiry guard keeps the no-due case O(1).
  s->gc(now_seconds());
  std::string k(key);
  auto it = s->data.find(k);
  if (it == s->data.end()) return ERR_NOT_FOUND;
  const std::string& v = it->second.value;
  *mod_rev = it->second.mod_rev;
  if (static_cast<int64_t>(v.size()) > buflen) return ERR_TOO_SMALL;
  std::memcpy(buf, v.data(), v.size());
  return static_cast<int64_t>(v.size());
}

// Buffer layout: u64 store_rev | u32 count | records...
int64_t kv_list(void* h, const char* prefix, uint8_t* buf, int64_t buflen) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  double now = now_seconds();
  s->gc(now);  // first-class expiry, same contract as kv_get
  std::string p(prefix);
  Writer w(buf, buflen);
  w.put<uint64_t>(s->rev);
  uint32_t count = 0;
  Writer counter(nullptr, 0);  // first pass to count
  for (auto it = s->data.lower_bound(p); it != s->data.end(); ++it) {
    if (it->first.compare(0, p.size(), p) != 0) break;
    if (s->expired(it->second, now)) continue;
    ++count;
  }
  w.put<uint32_t>(count);
  for (auto it = s->data.lower_bound(p); it != s->data.end(); ++it) {
    if (it->first.compare(0, p.size(), p) != 0) break;
    if (s->expired(it->second, now)) continue;
    w.put<uint64_t>(it->second.mod_rev);
    w.put_bytes(it->first);
    w.put_bytes(it->second.value);
  }
  if (!w.fits()) return -(w.size() + SIZE_HINT_BASE);  // size hint: grow + retry
  return w.size();
}

// All-or-nothing multi-key CAS commit (the binding tile fast path,
// core/store.py batch). expect_revs[i] 0 = no per-key CAS check.
int64_t kv_batch(void* h, uint64_t n, const char** keys,
                 const uint8_t** vals, const uint64_t* val_lens,
                 const uint64_t* expect_revs) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  s->gc(now_seconds());
  // validate everything first: a mid-batch failure commits nothing
  for (uint64_t i = 0; i < n; ++i) {
    auto it = s->data.find(keys[i]);
    if (it == s->data.end()) return ERR_NOT_FOUND;
    if (expect_revs[i] != 0 && it->second.mod_rev != expect_revs[i])
      return ERR_CONFLICT;
  }
  int64_t first_rev = 0;
  for (uint64_t i = 0; i < n; ++i) {
    auto it = s->data.find(keys[i]);
    uint64_t rev = s->bump();
    if (first_rev == 0) first_rev = static_cast<int64_t>(rev);
    it->second.value.assign(reinterpret_cast<const char*>(vals[i]),
                            val_lens[i]);
    it->second.mod_rev = rev;
    s->emit(rev, EventType::Modified, it->first, rev, it->second.value);
  }
  return first_rev;
}

// Batched create: every key must be absent (including duplicates
// WITHIN the batch) or nothing commits — the write-side analogue of
// kv_batch. Returns the first assigned revision, or ERR_EXISTS.
int64_t kv_create_batch(void* h, uint64_t n, const char** keys,
                        const uint8_t** vals, const uint64_t* val_lens,
                        const double* ttls) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  double now = now_seconds();
  s->gc(now);
  std::set<std::string> seen;
  for (uint64_t i = 0; i < n; ++i) {
    std::string k(keys[i]);
    if (s->data.count(k) || !seen.insert(k).second) return ERR_EXISTS;
  }
  int64_t first_rev = 0;
  for (uint64_t i = 0; i < n; ++i) {
    std::string k(keys[i]);
    uint64_t rev = s->bump();
    if (first_rev == 0) first_rev = static_cast<int64_t>(rev);
    Entry e{std::string(reinterpret_cast<const char*>(vals[i]),
                        val_lens[i]),
            rev, ttls[i] > 0 ? now + ttls[i] : 0};
    s->note_expiry(e.expiry);
    s->data[k] = e;
    s->emit(rev, EventType::Added, k, rev, e.value);
  }
  return first_rev;
}

// Events with rev > since_rev for keys under prefix.
// Layout: u32 count | event records... Returns bytes used, or
// -(required + SIZE_HINT_BASE) if the buffer is too small, or ERR_EXPIRED.
int64_t kv_events(void* h, uint64_t since_rev, const char* prefix,
                  uint8_t* buf, int64_t buflen) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  if (since_rev < s->oldest_rev) return ERR_EXPIRED;
  std::string p(prefix);
  Writer w(buf, buflen);
  // history is revision-ordered: binary-search the resume point so a
  // watcher poll costs O(log n + new events), not a full window scan
  auto begin = std::upper_bound(
      s->history.begin(), s->history.end(), since_rev,
      [](uint64_t rev, const Event& e) { return rev < e.rev; });
  uint32_t count = 0;
  for (auto it = begin; it != s->history.end(); ++it) {
    if (it->key.compare(0, p.size(), p) == 0) ++count;
  }
  w.put<uint32_t>(count);
  for (auto it = begin; it != s->history.end(); ++it) {
    const Event& e = *it;
    if (e.key.compare(0, p.size(), p) != 0) continue;
    w.put<uint64_t>(e.rev);
    w.put<uint8_t>(static_cast<uint8_t>(e.type));
    w.put_bytes(e.key);
    w.put<uint64_t>(e.obj_rev);
    w.put_bytes(e.value);
  }
  if (!w.fits()) return -(w.size() + SIZE_HINT_BASE);
  return w.size();
}

// ---------------------------------------------------------- recovery
// WAL recovery entry points (core/wal.py + NativeStore.recover): the
// Python side reads the snapshot + record tail and replays it here.

// Insert one snapshot entry with its original mod_rev and absolute
// expiry, emitting NO history event (snapshot state predates the
// replayable window). Advances the revision counter monotonically.
int64_t kv_restore(void* h, const char* key, const uint8_t* val,
                   uint64_t val_len, uint64_t mod_rev, double expiry) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  Entry e{std::string(reinterpret_cast<const char*>(val), val_len),
          mod_rev, expiry};
  s->note_expiry(expiry);
  s->data[std::string(key)] = e;
  if (mod_rev > s->rev) s->rev = mod_rev;
  return static_cast<int64_t>(mod_rev);
}

// Seal the snapshot restore point: revisions <= rev are not
// replayable from history (the watch-window meaning of oldest_rev).
void kv_restore_seal(void* h, uint64_t rev) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  if (rev > s->rev) s->rev = rev;
  s->oldest_rev = rev;
}

// Replay one ledger record at EXACTLY the given revision (the WAL
// tail). Unlike the write verbs, no gc runs and no revision is
// assigned here — the record's revision is authoritative, so replay
// reproduces the pre-crash ledger prefix bit-identically. obj_rev is
// the resourceVersion the delivered event stamps (pre-delete mod_rev
// for DELETED records).
int64_t kv_replay(void* h, uint64_t rev, uint8_t type, const char* key,
                  const uint8_t* val, uint64_t val_len, uint64_t obj_rev,
                  double expiry) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  if (rev <= s->rev) return ERR_CONFLICT;
  s->rev = rev;
  std::string k(key);
  std::string v(reinterpret_cast<const char*>(val), val_len);
  if (type == static_cast<uint8_t>(EventType::Deleted)) {
    s->data.erase(k);
    s->emit(rev, EventType::Deleted, k, obj_rev, v);
  } else {
    Entry e{v, rev, expiry};
    s->note_expiry(expiry);
    s->data[k] = e;
    s->emit(rev, static_cast<EventType>(type), k, rev, v);
  }
  return static_cast<int64_t>(rev);
}

// Replay one TXN frame's whole window (core/wal.py TXN records) in
// ONE lock window: the frame was one CRC unit on disk, so it recovers
// as one atomic unit in the engine too — mirroring kv_batch's commit
// shape. Revisions must be consecutive and start strictly after the
// current revision; per-record semantics are exactly kv_replay's.
// Returns the last replayed revision, or ERR_CONFLICT.
int64_t kv_replay_txn(void* h, uint64_t n, const uint64_t* revs,
                      const uint8_t* types, const char** keys,
                      const uint8_t** vals, const uint64_t* val_lens,
                      const uint64_t* obj_revs, const double* expiries) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  if (n == 0) return static_cast<int64_t>(s->rev);
  if (revs[0] <= s->rev) return ERR_CONFLICT;
  for (uint64_t i = 1; i < n; ++i)
    if (revs[i] != revs[0] + i) return ERR_CONFLICT;
  for (uint64_t i = 0; i < n; ++i) {
    uint64_t rev = revs[i];
    s->rev = rev;
    std::string k(keys[i]);
    std::string v(reinterpret_cast<const char*>(vals[i]), val_lens[i]);
    if (types[i] == static_cast<uint8_t>(EventType::Deleted)) {
      s->data.erase(k);
      s->emit(rev, EventType::Deleted, k, obj_revs[i], v);
    } else {
      Entry e{v, rev, expiries[i]};
      s->note_expiry(expiries[i]);
      s->data[k] = e;
      s->emit(rev, static_cast<EventType>(types[i]), k, rev, v);
    }
  }
  return static_cast<int64_t>(s->rev);
}

// Block until the store revision exceeds since_rev (or timeout).
// Returns the current revision. ctypes releases the GIL around this,
// so watcher threads park in native code, not in Python polling loops.
uint64_t kv_wait(void* h, uint64_t since_rev, double timeout_seconds) {
  Store* s = static_cast<Store*>(h);
  std::unique_lock<std::mutex> lk(s->mu);
  s->cv.wait_for(
      lk, std::chrono::duration<double>(timeout_seconds),
      [&] { return s->rev > since_rev; });
  return s->rev;
}

}  // extern "C"
