"""kubernetes_tpu — a TPU-native cluster orchestrator.

A brand-new framework with the capabilities of the reference Kubernetes
(v1.1-era) tree: declarative cluster-state API with list/watch, pluggable pod
scheduler, controllers, hollow-node agents and a kubemark-style scale harness —
with the control-plane *compute* (scheduler predicates/priorities) re-founded
on JAX/XLA as dense pods x nodes tensor math.

Package layout (see SURVEY.md section 7):
  core/      object schema, quantities, label/field selectors, codec,
             revisioned KV store with CAS + watch  (ref: pkg/api, pkg/runtime,
             pkg/labels, pkg/fields, pkg/storage)
  api/       REST server + clients + reflector/informer cache (ref:
             pkg/apiserver, pkg/registry, pkg/client)
  sched/     serial oracle scheduler (parity reference) + batch TPU engine
             (ref: plugin/pkg/scheduler)
  ops/       JAX predicate masks and priority scores (the device kernels)
  parallel/  mesh/sharding helpers, ICI-reduced argmax
  agents/    hollow node, controllers (ref: pkg/kubelet hollow mode,
             pkg/controller)
  cli/       kubectl-style CLI (ref: pkg/kubectl)
  utils/     trace, workqueue, backoff, rate limit, clock (ref: pkg/util)
"""

__version__ = "0.1.0"
