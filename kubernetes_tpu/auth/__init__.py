"""Request authentication and authorization.

Reference: pkg/auth/{authenticator,authorizer,user} interfaces with the
plugin set under plugin/pkg/auth (password/passwordfile basicauth,
tokenfile bearer tokens, union authenticators; ABAC file authorizer
pkg/apiserver/authz.go + pkg/auth/authorizer/abac). The API server wraps
its handler chain the way master.go:702,710 does: authenticate -> 401,
authorize -> 403, then route.
"""

from .authenticate import (Authenticator, BasicAuthAuthenticator,
                           TokenAuthenticator, UnionAuthenticator, UserInfo,
                           authenticate_request)
from .authorize import (ABACAuthorizer, AlwaysAllowAuthorizer,
                        AlwaysDenyAuthorizer, AuthorizerAttributes,
                        UnionAuthorizer, abac_from_lines)

__all__ = [
    "Authenticator", "BasicAuthAuthenticator", "TokenAuthenticator",
    "UnionAuthenticator", "UserInfo", "authenticate_request",
    "ABACAuthorizer", "AlwaysAllowAuthorizer", "AlwaysDenyAuthorizer",
    "AuthorizerAttributes", "UnionAuthorizer", "abac_from_lines",
]
