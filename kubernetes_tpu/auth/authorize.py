"""Request authorizers (ref: pkg/auth/authorizer + the ABAC file authorizer
pkg/auth/authorizer/abac: one JSON policy per line, empty/"*" fields match
everything, readonly restricts to GET/list/watch).
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import List, Optional, Sequence

from .authenticate import UserInfo


@dataclass
class AuthorizerAttributes:
    """(ref: authorizer.AttributesRecord)"""
    user: Optional[UserInfo] = None
    read_only: bool = False
    resource: str = ""
    namespace: str = ""


class AlwaysAllowAuthorizer:
    def authorize(self, attributes: AuthorizerAttributes) -> bool:
        return True


class AlwaysDenyAuthorizer:
    def authorize(self, attributes: AuthorizerAttributes) -> bool:
        return False


@dataclass
class ABACPolicy:
    """(ref: pkg/auth/authorizer/abac/types.go Policy)"""
    user: str = ""
    group: str = ""
    resource: str = ""
    namespace: str = ""
    readonly: bool = False

    def matches(self, attributes: AuthorizerAttributes) -> bool:
        info = attributes.user or UserInfo()
        if self.user and self.user != "*" and self.user != info.name:
            return False
        if self.group and self.group != "*" and \
                self.group not in info.groups:
            return False
        if self.readonly and not attributes.read_only:
            return False
        if self.resource and self.resource != "*" and \
                self.resource != attributes.resource:
            return False
        if self.namespace and self.namespace != "*" and \
                self.namespace != attributes.namespace:
            return False
        return True


class ABACAuthorizer:
    def __init__(self, policies: Sequence[ABACPolicy]):
        self.policies = list(policies)

    def authorize(self, attributes: AuthorizerAttributes) -> bool:
        return any(p.matches(attributes) for p in self.policies)


def abac_from_lines(lines: Sequence[str]) -> ABACAuthorizer:
    """One JSON object per non-blank, non-comment line (ref: abac/abac.go
    NewFromFile)."""
    policies: List[ABACPolicy] = []
    for i, line in enumerate(lines):
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            data = json.loads(line)
        except json.JSONDecodeError as e:
            raise ValueError(f"policy line {i + 1}: {e}")
        policies.append(ABACPolicy(
            user=data.get("user", ""),
            group=data.get("group", ""),
            resource=data.get("resource", ""),
            namespace=data.get("namespace", ""),
            readonly=bool(data.get("readonly", False))))
    return ABACAuthorizer(policies)


class UnionAuthorizer:
    """Any allow wins."""

    def __init__(self, authorizers: Sequence):
        self.authorizers = list(authorizers)

    def authorize(self, attributes: AuthorizerAttributes) -> bool:
        return any(a.authorize(attributes) for a in self.authorizers)
