"""Request authenticators (ref: pkg/auth/authenticator, plugin/pkg/auth:
password/{allow,passwordfile}, request/{basicauth,union}, token/tokenfile).
"""

from __future__ import annotations

import base64
import binascii
import hashlib
import hmac
import json
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple


@dataclass
class UserInfo:
    """(ref: pkg/auth/user.DefaultInfo)"""
    name: str = ""
    uid: str = ""
    groups: List[str] = field(default_factory=list)


class Authenticator:
    """Returns (UserInfo, ok). Never raises for bad credentials — a False
    lets union try the next method (ref: authenticator.Request)."""

    def authenticate(self, headers) -> Tuple[Optional[UserInfo], bool]:
        raise NotImplementedError


def _parse_basic(header: str):
    """-> (user, password) from a Basic Authorization header, or None
    (shared by every password authenticator so the parse can't drift)."""
    if not header.startswith("Basic "):
        return None
    try:
        decoded = base64.b64decode(header[6:]).decode()
    except (binascii.Error, UnicodeDecodeError):
        return None
    user, _, password = decoded.partition(":")
    return user, password


class BasicAuthAuthenticator(Authenticator):
    """HTTP basic auth against a user->password map (ref:
    plugin/pkg/auth/authenticator/request/basicauth +
    password/passwordfile; file format: password,user,uid per line)."""

    def __init__(self, passwords: Dict[str, Tuple[str, str]]):
        """passwords: user -> (password, uid)"""
        self.passwords = passwords

    @classmethod
    def from_lines(cls, lines: Sequence[str]) -> "BasicAuthAuthenticator":
        out: Dict[str, Tuple[str, str]] = {}
        for line in lines:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            parts = [p.strip() for p in line.split(",")]
            if len(parts) < 3:
                raise ValueError(
                    f"password file line needs password,user,uid: {line!r}")
            password, user, uid = parts[0], parts[1], parts[2]
            out[user] = (password, uid)
        return cls(out)

    def authenticate(self, headers) -> Tuple[Optional[UserInfo], bool]:
        parsed = _parse_basic(headers.get("Authorization", ""))
        if parsed is None:
            return None, False
        user, password = parsed
        entry = self.passwords.get(user)
        expected = entry[0] if entry is not None else ""
        # constant-time compare forecloses the timing side channel
        ok = hmac.compare_digest(expected.encode(), password.encode())
        if entry is None or not ok:
            return None, False
        return UserInfo(name=user, uid=entry[1]), True


class TokenAuthenticator(Authenticator):
    """Bearer tokens against a token->user map (ref:
    plugin/pkg/auth/authenticator/token/tokenfile; file format:
    token,user,uid per line)."""

    def __init__(self, tokens: Dict[str, UserInfo]):
        self.tokens = tokens
        self._by_digest = {
            hashlib.sha256(t.encode()).hexdigest(): (t, u)
            for t, u in tokens.items()}

    @classmethod
    def from_lines(cls, lines: Sequence[str]) -> "TokenAuthenticator":
        out: Dict[str, UserInfo] = {}
        for line in lines:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            parts = [p.strip() for p in line.split(",")]
            if len(parts) < 3:
                raise ValueError(
                    f"token file line needs token,user,uid: {line!r}")
            out[parts[0]] = UserInfo(name=parts[1], uid=parts[2],
                                     groups=parts[3:])
        return cls(out)

    def authenticate(self, headers) -> Tuple[Optional[UserInfo], bool]:
        header = headers.get("Authorization", "")
        if not header.startswith("Bearer "):
            return None, False
        presented = header[7:]
        # probe by digest, then one constant-time compare of the stored
        # token — O(1) per request with no token-prefix timing channel
        digest = hashlib.sha256(presented.encode()).hexdigest()
        entry = self._by_digest.get(digest)
        if entry is None or not hmac.compare_digest(
                entry[0].encode(), presented.encode()):
            return None, False
        return entry[1], True


def _b64url_decode(part: str) -> bytes:
    pad = "=" * (-len(part) % 4)
    return base64.urlsafe_b64decode(part + pad)


class JWTAuthenticator(Authenticator):
    """OIDC bearer JWTs: signature + iss/aud/exp claims checked,
    identity from configurable claims.

    Reference: plugin/pkg/auth/authenticator/token/oidc (flags
    --oidc-issuer-url/-client-id/-username-claim/-groups-claim;
    oidc.go verifies RS256 against the provider's JWKS). RS256 is
    verified here with pure-Python PKCS#1 v1.5 (auth/rsa.py) against a
    JWKS document; HS256 against a shared secret stays for the local
    identity-provider role. Algorithm dispatch is strict — an RS256
    public key can never be used as an HS256 secret (the classic JWT
    alg-confusion downgrade), because each algorithm only consults its
    own key material and a missing secret/jwks rejects outright."""

    def __init__(self, secret: Optional[bytes] = None, issuer: str = "",
                 audience: str = "", username_claim: str = "sub",
                 groups_claim: str = "groups", clock=None,
                 jwks: Optional[dict] = None):
        self.secret = secret
        self.issuer = issuer
        self.audience = audience
        self.username_claim = username_claim
        self.groups_claim = groups_claim
        self._now = clock or time.time
        from . import rsa as rsapkg
        self._rsa = rsapkg
        self._rsa_keys = rsapkg.jwks_rsa_keys(jwks) if jwks else []

    def _signature_ok(self, head: dict, parts: List[str]) -> bool:
        alg = head.get("alg")
        signing_input = f"{parts[0]}.{parts[1]}".encode()
        sig = _b64url_decode(parts[2])
        if alg == "HS256":
            if not self.secret:
                return False
            expected = hmac.new(self.secret, signing_input,
                                hashlib.sha256).digest()
            return hmac.compare_digest(expected, sig)
        if alg == "RS256":
            kid = head.get("kid")
            candidates = [(k, n, e) for k, n, e in self._rsa_keys
                          if kid is None or k is None or k == kid]
            return any(
                self._rsa.verify_pkcs1v15_sha256(n, e, signing_input, sig)
                for _k, n, e in candidates)
        return False  # unknown or absent alg (incl. "none"): reject

    def authenticate(self, headers) -> Tuple[Optional[UserInfo], bool]:
        header = headers.get("Authorization", "")
        if not header.startswith("Bearer "):
            return None, False
        token = header[7:]
        parts = token.split(".")
        if len(parts) != 3:
            return None, False
        try:
            import json
            head = json.loads(_b64url_decode(parts[0]))
            if not self._signature_ok(head, parts):
                return None, False
            claims = json.loads(_b64url_decode(parts[1]))
        except (ValueError, binascii.Error):
            return None, False
        if self.issuer and claims.get("iss") != self.issuer:
            return None, False
        if self.audience:
            aud = claims.get("aud")
            auds = aud if isinstance(aud, list) else [aud]
            if self.audience not in auds:
                return None, False
        exp = claims.get("exp")
        if exp is not None:
            try:
                if float(exp) <= self._now():
                    return None, False
            except (TypeError, ValueError):
                return None, False  # unparseable exp: reject, not 500
        name = claims.get(self.username_claim)
        if not name:
            return None, False
        groups = claims.get(self.groups_claim) or []
        if not isinstance(groups, list):
            groups = [groups]
        return UserInfo(name=str(name), uid=str(claims.get("sub", "")),
                        groups=[str(g) for g in groups]), True


def _b64url_encode_json(obj) -> str:
    import json
    raw = json.dumps(obj, separators=(",", ":")).encode()
    return base64.urlsafe_b64encode(raw).rstrip(b"=").decode()


def make_jwt(secret: bytes, claims: dict, header: Optional[dict] = None
             ) -> str:
    """Mint an HS256 JWT (tests + local identity provider role).
    `header` overrides let tests forge alg-confusion headers."""
    head = _b64url_encode_json(header or {"alg": "HS256", "typ": "JWT"})
    body = _b64url_encode_json(claims)
    sig = hmac.new(secret, f"{head}.{body}".encode(),
                   hashlib.sha256).digest()
    return (f"{head}.{body}."
            f"{base64.urlsafe_b64encode(sig).rstrip(b'=').decode()}")


def make_jwt_rs256(key: Dict[str, int], claims: dict, kid: str = ""
                   ) -> str:
    """Mint an RS256 JWT with an auth.rsa keypair dict {'n','e','d'}
    (tests + local identity provider role)."""
    from . import rsa as rsapkg
    header = {"alg": "RS256", "typ": "JWT"}
    if kid:
        header["kid"] = kid
    head = _b64url_encode_json(header)
    body = _b64url_encode_json(claims)
    sig = rsapkg.sign_pkcs1v15_sha256(key["n"], key["d"],
                                      f"{head}.{body}".encode())
    return (f"{head}.{body}."
            f"{base64.urlsafe_b64encode(sig).rstrip(b'=').decode()}")


class X509Authenticator(Authenticator):
    """Client-certificate auth: CommonName -> user, Organization ->
    groups, from the CA-verified TLS peer subject the ApiServer injects
    as the X-Peer-Certificate pseudo-header (the server strips any
    client-supplied copy, so the header only ever carries what the TLS
    layer verified). Ref: plugin/pkg/auth/authenticator/request/x509
    CommonNameUserConversion."""

    def authenticate(self, headers) -> Tuple[Optional[UserInfo], bool]:
        raw = headers.get("X-Peer-Certificate", "")
        if not raw:
            return None, False
        try:
            subject = json.loads(raw)
        except ValueError:
            return None, False
        cn = ""
        orgs = []
        # ssl.getpeercert subject: sequence of RDNs, each a sequence of
        # (attribute, value) pairs
        for rdn in subject:
            for pair in rdn:
                if len(pair) != 2:
                    continue
                attr, value = pair
                if attr == "commonName" and not cn:
                    cn = value
                elif attr == "organizationName":
                    orgs.append(value)
        if not cn:
            return None, False
        return UserInfo(name=cn, groups=orgs), True


class KeystonePasswordAuthenticator(Authenticator):
    """Basic-auth credentials validated against an external identity
    service speaking the Keystone v2 tokens API (POST {auth_url}/tokens
    with passwordCredentials; any 2xx authenticates).

    Reference: plugin/pkg/auth/authenticator/request/keystone/
    keystone.go — AuthenticatePassword delegates the check to the
    keystone endpoint and returns DefaultInfo{Name: username}. Same
    https-only constraint (keystone.go NewKeystoneAuthenticator), with
    an explicit escape hatch for tests."""

    def __init__(self, auth_url: str, timeout: float = 10.0,
                 allow_insecure_for_tests: bool = False):
        if not auth_url:
            raise ValueError("auth URL is empty")
        if not auth_url.startswith("https") and not allow_insecure_for_tests:
            raise ValueError(
                "auth URL should be secure and start with https")
        self.auth_url = auth_url.rstrip("/")
        self.timeout = timeout

    def _validate(self, username: str, password: str) -> bool:
        import json as jsonlib
        import urllib.error
        import urllib.request
        body = jsonlib.dumps({"auth": {"passwordCredentials": {
            "username": username, "password": password}}}).encode()
        req = urllib.request.Request(
            self.auth_url + "/tokens", data=body,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                return 200 <= r.status < 300
        except (urllib.error.URLError, OSError, ValueError):
            return False

    def authenticate(self, headers) -> Tuple[Optional[UserInfo], bool]:
        parsed = _parse_basic(headers.get("Authorization", ""))
        if parsed is None:
            return None, False
        user, password = parsed
        if not user or not self._validate(user, password):
            return None, False
        return UserInfo(name=user), True


class UnionAuthenticator(Authenticator):
    """First success wins (ref: request/union)."""

    def __init__(self, authenticators: Sequence[Authenticator]):
        self.authenticators = list(authenticators)

    def authenticate(self, headers) -> Tuple[Optional[UserInfo], bool]:
        for a in self.authenticators:
            info, ok = a.authenticate(headers)
            if ok:
                return info, True
        return None, False


def authenticate_request(authenticator: Optional[Authenticator],
                         headers) -> Tuple[Optional[UserInfo], bool]:
    """None authenticator = open server (every request is anonymous ok)."""
    if authenticator is None:
        return UserInfo(name="system:anonymous"), True
    return authenticator.authenticate(headers)
