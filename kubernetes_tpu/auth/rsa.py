"""Pure-Python RSA PKCS#1 v1.5 for OIDC RS256 token verification.

Reference: plugin/pkg/auth/authenticator/token/oidc/oidc.go validates
RS256 ID tokens against the provider's JWKS. The verify side is modular
exponentiation plus a byte-exact EMSA-PKCS1-v1_5 comparison (RFC 3447
section 8.2.2) — no crypto dependency needed. The signing/keygen half
exists so tests and the local identity-provider role can mint RS256
tokens; production verification never uses it.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import secrets
from typing import Dict, List, Optional, Tuple

# DigestInfo DER prefix for SHA-256 (RFC 3447 section 9.2 note 1)
_SHA256_DER_PREFIX = bytes.fromhex(
    "3031300d060960864801650304020105000420")


def _b64url_uint(data: str) -> int:
    pad = "=" * (-len(data) % 4)
    return int.from_bytes(base64.urlsafe_b64decode(data + pad), "big")


def _emsa_pkcs1_v15_sha256(message: bytes, k: int) -> Optional[bytes]:
    """EM = 0x00 0x01 PS 0x00 DigestInfo, len k (RFC 3447 9.2)."""
    t = _SHA256_DER_PREFIX + hashlib.sha256(message).digest()
    if k < len(t) + 11:
        return None
    return b"\x00\x01" + b"\xff" * (k - len(t) - 3) + b"\x00" + t


def verify_pkcs1v15_sha256(n: int, e: int, message: bytes,
                           signature: bytes) -> bool:
    """RSASSA-PKCS1-V1_5-VERIFY with SHA-256: encode-then-compare
    (byte-exact against the full EM, so padding malleability variants
    are rejected, not parsed)."""
    k = (n.bit_length() + 7) // 8
    if len(signature) != k:
        return False
    s = int.from_bytes(signature, "big")
    if s >= n:
        return False
    em = pow(s, e, n).to_bytes(k, "big")
    expected = _emsa_pkcs1_v15_sha256(message, k)
    if expected is None:
        return False
    return hmac.compare_digest(em, expected)


# ------------------------------------------------------------------ JWKS

def jwks_rsa_keys(jwks: dict) -> List[Tuple[Optional[str], int, int]]:
    """[(kid, n, e)] for every usable RSA key in a JWKS document
    (unknown kty / malformed entries are skipped, as the reference's
    provider sync does)."""
    out = []
    for key in jwks.get("keys", []):
        if not isinstance(key, dict) or key.get("kty") != "RSA":
            continue
        try:
            n = _b64url_uint(key["n"])
            e = _b64url_uint(key["e"])
        except (KeyError, ValueError, TypeError):
            continue
        if n <= 0 or e <= 0:
            continue
        out.append((key.get("kid"), n, e))
    return out


# ---------------------------------------------------- test-side keygen

_SMALL_PRIMES = [3, 5, 7, 11, 13, 17, 19, 23, 29, 31, 37, 41, 43, 47,
                 53, 59, 61, 67, 71, 73, 79, 83, 89, 97]


def _is_probable_prime(n: int, rounds: int = 40) -> bool:
    if n < 2:
        return False
    for p in _SMALL_PRIMES:
        if n % p == 0:
            return n == p
    d, r = n - 1, 0
    while d % 2 == 0:
        d //= 2
        r += 1
    for _ in range(rounds):
        a = secrets.randbelow(n - 3) + 2
        x = pow(a, d, n)
        if x in (1, n - 1):
            continue
        for _ in range(r - 1):
            x = (x * x) % n
            if x == n - 1:
                break
        else:
            return False
    return True


def _random_prime(bits: int) -> int:
    while True:
        p = secrets.randbits(bits) | (1 << (bits - 1)) | 1
        if _is_probable_prime(p):
            return p


def generate_keypair(bits: int = 1024) -> Dict[str, int]:
    """{'n','e','d'} — small-modulus keys for tests (not production
    key material; the authenticator only ever verifies)."""
    e = 65537
    while True:
        p = _random_prime(bits // 2)
        q = _random_prime(bits // 2)
        if p == q:
            continue
        n = p * q
        lam = (p - 1) * (q - 1)
        if lam % e == 0:
            continue
        d = pow(e, -1, lam)
        return {"n": n, "e": e, "d": d}


def sign_pkcs1v15_sha256(n: int, d: int, message: bytes) -> bytes:
    k = (n.bit_length() + 7) // 8
    em = _emsa_pkcs1_v15_sha256(message, k)
    if em is None:
        raise ValueError("modulus too small for SHA-256 DigestInfo")
    return pow(int.from_bytes(em, "big"), d, n).to_bytes(k, "big")


def jwk_of(n: int, e: int, kid: str = "") -> dict:
    def b64(i: int) -> str:
        raw = i.to_bytes((i.bit_length() + 7) // 8, "big")
        return base64.urlsafe_b64encode(raw).rstrip(b"=").decode()
    key = {"kty": "RSA", "n": b64(n), "e": b64(e), "alg": "RS256",
           "use": "sig"}
    if kid:
        key["kid"] = kid
    return key
