"""Accelerator platform probing for standalone entry points.

The default platform may be a tunneled TPU whose wedged state hangs the
FIRST dispatch (even backend creation) forever. Every standalone
benchmark/driver entry must therefore probe the platform in a timed
subprocess before any in-process jax dispatch, and fall back to CPU —
recording which platform actually ran — rather than hang.
(The same discipline __graft_entry__.dryrun_multichip applies.)
"""

from __future__ import annotations

import os
import subprocess
import sys
from typing import Optional

_CHECKED_ENV = "KTPU_PLATFORM_CHECKED"
_DIAG_ENV = "KTPU_PROBE_DIAG"


def pin_cpu() -> str:
    """Pin the CPU platform BEFORE jax backend init and return the
    platform label for the artifact. JAX_PLATFORMS alone is not enough
    on this image — sitecustomize registers the axon TPU plugin and
    pins jax_platforms past the env var — so every cpu-pinned entry
    point (tests/conftest.py, tools/density_matrix.py --cpu,
    kubemark/soak.py --cpu) must make this exact config move."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    return "cpu-pinned"


def probe_default_platform(timeout: float = 180.0) -> bool:
    """True iff a tiny dispatch completes on the default platform in a
    clean subprocess within the timeout."""
    probe = ("import jax, jax.numpy as jnp; "
             "jnp.ones(4).sum().block_until_ready(); print('ok')")
    try:
        return subprocess.run(
            [sys.executable, "-c", probe], capture_output=True,
            timeout=timeout).returncode == 0
    except subprocess.TimeoutExpired:
        return False


def probe_with_retries(attempts: int = 1, timeout: float = 180.0,
                       backoff: float = 30.0) -> dict:
    """The tunnel wedges for hours but recovers; retry the probe a few
    times and return the diagnostics either way."""
    import time
    history = []
    for i in range(attempts):
        t0 = time.time()
        ok = probe_default_platform(timeout)
        history.append({"attempt": i + 1, "ok": ok,
                        "elapsed_s": round(time.time() - t0, 1)})
        if ok:
            return {"healthy": True, "attempts": history}
        if i + 1 < attempts:
            time.sleep(backoff)
    return {"healthy": False, "attempts": history}


def ensure_live_platform(attempts: int = 1,
                         timeout: float = 180.0) -> tuple:
    """Probe the default platform; on failure re-exec this process with
    JAX_PLATFORMS=cpu (the env var alone is not enough past the image's
    sitecustomize platform pin, so the re-exec'd run must ALSO call
    jax.config.update — done here when the marker env var is present).

    -> (platform, probe_diagnostics): "default" or "cpu-fallback" plus
    the retry history (both belong in every benchmark artifact so
    numbers are attributable to hardware)."""
    import json
    if os.environ.get(_CHECKED_ENV):
        plat = os.environ.get("JAX_PLATFORMS", "")
        diag = json.loads(os.environ.get(_DIAG_ENV, "{}") or "{}")
        if plat:
            import jax
            jax.config.update("jax_platforms", plat)
            return ("cpu-fallback" if plat == "cpu" else "default"), diag
        return "default", diag
    diag = probe_with_retries(attempts, timeout)
    os.environ[_CHECKED_ENV] = "1"
    os.environ[_DIAG_ENV] = json.dumps(diag)
    if not diag["healthy"]:
        env = {**os.environ, "JAX_PLATFORMS": "cpu"}
        # orig_argv replays the exact invocation (`python -m pkg.mod`
        # included — re-execing sys.argv[0] as a script would break
        # relative imports for -m entry points)
        os.execve(sys.executable, list(sys.orig_argv), env)
    return "default", diag
