"""Generational-GC tuning for steady-state control-plane processes.

The API dataclasses form no reference cycles (plain trees: object ->
metadata/spec/status -> lists of leaf dataclasses), so CPython's
refcounting reclaims essentially all garbage and the cyclic collector
only costs: each gen-0 pass scans every tracked young object, and with
a 5k-node fleet churning ~10 clones per pod the collector fired often
enough to show at ~25% of profile ticks (PROFILE_e2e.md,
_xla_gc_callback — jax registers a hook that runs on every
collection, so collections are extra-expensive in-process).

The tuning a long-lived server applies at startup (the same move Go's
runtime makes structurally — its GC is concurrent, ours stops the
world): freeze the boot-time object graph out of the young
generations, then raise gen-0's threshold so steady-state churn is
reclaimed by refcounting with rare cycle sweeps. The collector stays
ON — genuine cycles (error tracebacks etc.) still get collected.

Used by the hyperkube server entries and the kubemark benchmark
(a warm live scheduler measures with the same process tuning it
serves with).
"""

from __future__ import annotations

import contextlib
import gc

TUNED_THRESHOLD = (50_000, 20, 20)


def tune_for_server() -> None:
    """One-way startup tuning for a real server process."""
    gc.collect()
    gc.freeze()
    gc.set_threshold(*TUNED_THRESHOLD)


@contextlib.contextmanager
def tuned_gc():
    """Scoped variant for benchmarks/tests: tune, then restore (and
    unfreeze) so the host process's GC behavior is unchanged after."""
    prev = gc.get_threshold()
    gc.collect()
    gc.freeze()
    gc.set_threshold(*TUNED_THRESHOLD)
    try:
        yield
    finally:
        gc.set_threshold(*prev)
        gc.unfreeze()
        gc.collect()
