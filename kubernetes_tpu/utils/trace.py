"""Span-with-steps trace logger (ref: pkg/util/trace.go:17-60): record named
steps; log the whole span only if it exceeded a threshold. Used around REST
handlers and the scheduler's batch compile/execute path, like the reference
uses it in resthandler.go and etcd_helper.go."""

from __future__ import annotations

import logging
import time
from typing import List, Optional, Tuple

logger = logging.getLogger("kubernetes_tpu.trace")


class Trace:
    def __init__(self, name: str):
        self.name = name
        self.start = time.monotonic()
        self.steps: List[Tuple[float, str]] = []

    def step(self, msg: str) -> None:
        self.steps.append((time.monotonic(), msg))

    def total_seconds(self) -> float:
        return time.monotonic() - self.start

    def log_if_long(self, threshold_seconds: float) -> None:
        if self.total_seconds() >= threshold_seconds:
            self.log()

    def log(self) -> None:
        total = self.total_seconds()
        lines = [f'Trace "{self.name}" (total {total*1000:.1f}ms):']
        prev = self.start
        for ts, msg in self.steps:
            lines.append(f"  [{(ts - prev)*1000:8.1f}ms] {msg}")
            prev = ts
        logger.info("\n".join(lines))
