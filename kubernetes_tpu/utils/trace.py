"""Over-threshold span logger (ref: pkg/util/trace.go:17-60): record
named steps; log the whole span only if it exceeded a threshold, the
way the reference wraps REST handlers (resthandler.go) and etcd calls
(etcd_helper.go).

Since the obs layer landed this is a VIEW, not a recorder: a Trace
opens a real obs span (so its interval and step marks reach the span
buffer, the Perfetto export, and any stage summaries like every other
span) and keeps only the glog-style formatting here. Time comes from
the tracer's injectable utils/clock.Clock — never a hardwired
time.monotonic() — so harnesses driving a FakeClock replay the
threshold decision too.
"""

from __future__ import annotations

import logging
from typing import List, Tuple

logger = logging.getLogger("kubernetes_tpu.trace")


class Trace:
    def __init__(self, name: str):
        # local import: utils is a leaf package obs itself imports
        from .. import obs
        self._tracer = obs.tracer()
        self._span = self._tracer.start_span(name, parent=obs.current())
        self.name = name
        self.start = (self._tracer.clock.monotonic()
                      if self._span is obs.NOOP else self._span.start)

    @property
    def steps(self) -> List[Tuple[float, str]]:
        return list(self._span.steps)

    def step(self, msg: str) -> None:
        self._tracer.step(self._span, msg)

    def total_seconds(self) -> float:
        return self._tracer.clock.monotonic() - self.start

    def finish(self) -> None:
        """Seal the underlying span (idempotent via the end guard)."""
        if self._span.end is None:
            self._tracer.end(self._span)

    def log_if_long(self, threshold_seconds: float) -> None:
        long = self.total_seconds() >= threshold_seconds
        self.finish()
        if long:
            self.log()

    def log(self) -> None:
        total = self.total_seconds()
        lines = [f'Trace "{self.name}" (total {total*1000:.1f}ms):']
        prev = self.start
        for ts, msg in self._span.steps:
            lines.append(f"  [{(ts - prev)*1000:8.1f}ms] {msg}")
            prev = ts
        logger.info("\n".join(lines))
