"""Per-entry exponential backoff (ref: plugin/pkg/scheduler/factory/
factory.go:376-452 podBackoff — 1s doubling to 60s, garbage-collected)."""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

from .clock import Clock, RealClock


class Backoff:
    def __init__(self, initial: float = 1.0, max_duration: float = 60.0,
                 clock: Optional[Clock] = None):
        self.initial = initial
        self.max = max_duration
        self.clock = clock or RealClock()
        self._lock = threading.Lock()
        # id -> (current_backoff_seconds, last_update_ts)
        self._entries: Dict[str, Tuple[float, float]] = {}

    def get(self, key: str) -> float:
        """Current backoff for key, doubling it for next time."""
        now = self.clock.now()
        with self._lock:
            duration, _ = self._entries.get(key, (self.initial, now))
            self._entries[key] = (min(duration * 2, self.max), now)
            return duration

    def wait(self, key: str) -> None:
        self.clock.sleep(self.get(key))

    def reset(self, key: str) -> None:
        with self._lock:
            self._entries.pop(key, None)

    def gc(self, max_age: float = 2 * 60.0) -> None:
        now = self.clock.now()
        with self._lock:
            stale = [k for k, (_, ts) in self._entries.items()
                     if now - ts > max_age]
            for k in stale:
                del self._entries[k]
