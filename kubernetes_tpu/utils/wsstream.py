"""Websocket framing + handshakes — the pkg/util/wsstream role.

The reference upgrades exec/attach/portForward to SPDY streams
(pkg/util/httpstream) and serves watches over websockets
(pkg/util/wsstream); SPDY is dead on the modern web, so every upgraded
stream here is RFC 6455. One implementation serves the apiserver's
websocket watch, the kubelet's portForward endpoint, the apiserver's
portforward relay, and kubectl's local bridge.

Port-forward data plane: binary frames carry raw TCP bytes. TCP
half-close (a client that sends its request then shutdown(SHUT_WR) and
reads the response) has no websocket equivalent, so an in-band TEXT
frame with payload EOF_MARKER propagates it: the receiver shuts the
write side of its TCP leg and keeps pumping the other direction. A
CLOSE frame ends the whole session (the pod-facing side sends it when
the pod connection reaches EOF — the response is complete).
"""

from __future__ import annotations

import base64
import hashlib
import os
import socket
import threading
from typing import Callable, Dict, Optional, Tuple

_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"

TEXT = 0x1
BINARY = 0x2
CLOSE = 0x8
PING = 0x9
PONG = 0xA

EOF_MARKER = b"\x00ws-half-close"

# One frame's payload bound. Port-forward pumps emit <=64KiB frames;
# anything bigger from a peer is hostile or broken — without a cap one
# forged 2^40-byte length would make _read_exact buffer until OOM.
MAX_FRAME = 1 << 20


def accept_key(key: str) -> str:
    return base64.b64encode(
        hashlib.sha1((key + _GUID).encode()).digest()).decode()


def server_handshake(h) -> bool:
    """Answer a BaseHTTPRequestHandler's upgrade request with 101.
    Returns False (and a 400) if the client sent no websocket key."""
    key = h.headers.get("Sec-WebSocket-Key", "")
    if not key:
        h.send_response(400)
        h.end_headers()
        return False
    h.send_response(101, "Switching Protocols")
    h.send_header("Upgrade", "websocket")
    h.send_header("Connection", "Upgrade")
    h.send_header("Sec-WebSocket-Accept", accept_key(key))
    h.end_headers()
    return True


def client_connect(host: str, port: int, path: str,
                   timeout: float = 30.0,
                   headers: Optional[Dict[str, str]] = None,
                   ssl_context=None, sock=None) -> socket.socket:
    """Open a websocket as a client: TCP connect (TLS-wrapped when an
    ssl_context is given), HTTP upgrade carrying any extra headers
    (Authorization — the kubeconfig credential role). Returns the socket
    positioned after the 101 response headers.

    sock: an already-connected transport (anything with sendall/recv/
    settimeout/close — e.g. a tunneler TunnelConn) to upgrade in place
    instead of dialing; ssl_context is ignored then."""
    if sock is None:
        sock = socket.create_connection((host, port), timeout=timeout)
    try:
        if ssl_context is not None and isinstance(sock, socket.socket):
            sock = ssl_context.wrap_socket(sock, server_hostname=host)
        key = base64.b64encode(os.urandom(16)).decode()
        extra = "".join(f"{k}: {v}\r\n" for k, v in (headers or {}).items())
        req = (f"GET {path} HTTP/1.1\r\n"
               f"Host: {host}:{port}\r\n"
               "Upgrade: websocket\r\n"
               "Connection: Upgrade\r\n"
               f"Sec-WebSocket-Key: {key}\r\n"
               "Sec-WebSocket-Version: 13\r\n"
               f"{extra}\r\n")
        sock.sendall(req.encode())
        buf = b""
        while b"\r\n\r\n" not in buf:
            chunk = sock.recv(4096)
            if not chunk:
                raise ConnectionError("upgrade: connection closed")
            buf += chunk
            if len(buf) > 65536:
                raise ConnectionError("upgrade: oversized response")
        head, rest = buf.split(b"\r\n\r\n", 1)
        status = head.split(b"\r\n", 1)[0]
        if b"101" not in status:
            raise ConnectionError(f"upgrade refused: {status.decode()}")
        sock.settimeout(None)
        if rest:
            # server-speaks-first targets (SMTP/SSH banners): the pod's
            # first frame can coalesce with the 101 — hand the leftover
            # bytes back ahead of the socket
            return _PrefixedSocket(sock, rest)
        return sock
    except BaseException:
        sock.close()
        raise


class _PrefixedSocket:
    """A socket whose recv drains buffered bytes first (the tail of the
    TCP segment that carried the upgrade response). Delegates the rest
    of the socket surface."""

    def __init__(self, sock: socket.socket, prefix: bytes):
        self._sock = sock
        self._prefix = prefix

    def recv(self, n: int) -> bytes:
        if self._prefix:
            out, self._prefix = self._prefix[:n], self._prefix[n:]
            return out
        return self._sock.recv(n)

    def __getattr__(self, name):
        return getattr(self._sock, name)


def _xor_mask(payload: bytes, key: bytes) -> bytes:
    """RFC 6455 masking. A per-byte Python loop caps the forward data
    plane at tens of MB/s; XOR of big ints runs at memcpy-ish speed for
    the 64KiB frames the pumps emit."""
    n = len(payload)
    reps = (n + 3) // 4
    p = int.from_bytes(payload, "little")
    m = int.from_bytes((key * reps)[:n], "little")
    return (p ^ m).to_bytes(n, "little")


def _read_exact(read: Callable[[int], bytes], n: int) -> bytes:
    out = bytearray()
    while len(out) < n:
        chunk = read(n - len(out))
        if not chunk:
            raise ConnectionError("websocket: short read")
        out += chunk
    return bytes(out)


def read_frame(read: Callable[[int], bytes]) -> Tuple[int, bytes]:
    """-> (opcode, payload), unmasking if the client masked (clients
    MUST mask per RFC 6455; servers must not). Frames beyond MAX_FRAME
    are rejected before any payload is buffered."""
    head = _read_exact(read, 2)
    opcode = head[0] & 0x0F
    masked = bool(head[1] & 0x80)
    ln = head[1] & 0x7F
    if ln == 126:
        ln = int.from_bytes(_read_exact(read, 2), "big")
    elif ln == 127:
        ln = int.from_bytes(_read_exact(read, 8), "big")
    if ln > MAX_FRAME:
        raise ConnectionError(f"websocket: {ln}-byte frame exceeds cap")
    mask = _read_exact(read, 4) if masked else b""
    payload = _read_exact(read, ln) if ln else b""
    if masked and payload:
        payload = _xor_mask(payload, mask)
    return opcode, payload


def write_frame(write: Callable[[bytes], None], payload: bytes,
                opcode: int = BINARY, mask: bool = False) -> None:
    head = bytes([0x80 | opcode])
    n = len(payload)
    if n < 126:
        head += bytes([(0x80 if mask else 0) | n])
    elif n < 1 << 16:
        head += bytes([(0x80 if mask else 0) | 126]) + n.to_bytes(2, "big")
    else:
        head += bytes([(0x80 if mask else 0) | 127]) + n.to_bytes(8, "big")
    if mask:
        key = os.urandom(4)
        write(head + key + _xor_mask(payload, key))
    else:
        write(head + payload)


def _pump_sock_to_ws(sock: socket.socket, write: Callable[[bytes], None],
                     mask: bool, close_on_eof: bool) -> None:
    """TCP bytes -> binary frames. On EOF: the pod-facing side sends
    CLOSE (the response stream is complete — the session is over); the
    client side sends the half-close marker and lets the reverse
    direction keep flowing."""
    try:
        while True:
            data = sock.recv(65536)
            if not data:
                break
            write_frame(write, data, BINARY, mask=mask)
        write_frame(write, b"" if close_on_eof else EOF_MARKER,
                    CLOSE if close_on_eof else TEXT, mask=mask)
    except (ConnectionError, OSError, ValueError):
        try:
            write_frame(write, b"", CLOSE, mask=mask)
        except (ConnectionError, OSError, ValueError):
            pass


def _pump_ws_to_sock(read: Callable[[int], bytes],
                     sock: socket.socket) -> str:
    """Frames -> TCP bytes. Returns 'close' (peer ended the session),
    'eof' (peer half-closed; reverse data may still flow), or 'error'."""
    try:
        while True:
            opcode, payload = read_frame(read)
            if opcode == CLOSE:
                try:
                    sock.shutdown(socket.SHUT_WR)
                except OSError:
                    pass
                return "close"
            if opcode == TEXT and payload == EOF_MARKER:
                try:
                    sock.shutdown(socket.SHUT_WR)
                except OSError:
                    pass
                return "eof"
            if opcode in (PING, PONG):
                continue
            if payload:
                sock.sendall(payload)
    except (ConnectionError, OSError, ValueError):
        try:
            sock.shutdown(socket.SHUT_WR)
        except OSError:
            pass
        return "error"


def bridge(ws_read: Callable[[int], bytes],
           ws_write: Callable[[bytes], None],
           sock: socket.socket, mask: bool = False,
           pod_side: bool = False) -> None:
    """Bidirectional ws <-> TCP pump. Returns when the session is over:
    both directions drained, or the peer sent CLOSE, or transport error.
    pod_side=True marks the leg whose sock EOF means 'session complete'
    (the kubelet sends CLOSE then); the client leg propagates local EOF
    as a half-close marker instead. Caller closes sock afterwards."""
    t = threading.Thread(
        target=_pump_sock_to_ws, args=(sock, ws_write, mask,
                                       pod_side), daemon=True)
    t.start()
    reason = _pump_ws_to_sock(ws_read, sock)
    if reason in ("close", "error"):
        # session over: unblock the reader thread's recv
        try:
            sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
    # 'eof': the peer half-closed — keep pumping sock -> ws until the
    # sock side finishes (that is the whole point of half-close)
    t.join()


def _pump_ws_to_ws(read: Callable[[int], bytes],
                   write: Callable[[bytes], None], mask: bool) -> None:
    """Re-frame from one websocket to another, preserving data opcodes
    (the half-close TEXT marker must survive the relay). Forwards CLOSE
    and ends."""
    try:
        while True:
            opcode, payload = read_frame(read)
            if opcode == CLOSE:
                write_frame(write, b"", CLOSE, mask=mask)
                return
            if opcode in (PING, PONG):
                continue
            write_frame(write, payload, opcode, mask=mask)
    except (ConnectionError, OSError, ValueError):
        try:
            write_frame(write, b"", CLOSE, mask=mask)
        except (ConnectionError, OSError, ValueError):
            pass


def relay_ws(down_read: Callable[[int], bytes],
             down_write: Callable[[bytes], None],
             up_sock: socket.socket) -> None:
    """Bidirectional websocket relay: downstream server leg <-> an
    already-upgraded upstream client socket (the apiserver's
    portforward middle leg; upstream writes are re-masked because the
    relay is itself a client). Returns when both directions are done;
    caller closes up_sock."""

    def up_write(b: bytes) -> None:
        up_sock.sendall(b)

    def up_read(n: int) -> bytes:
        return up_sock.recv(n)

    t = threading.Thread(target=_pump_ws_to_ws,
                         args=(up_read, down_write, False), daemon=True)
    t.start()
    _pump_ws_to_ws(down_read, up_write, True)
    # downstream leg done (client closed or sent CLOSE): unblock the
    # upstream reader so its pump can forward the final CLOSE and end.
    # up_sock may be a plain socket, a TunnelConn, or a _PrefixedSocket
    # over either — anything socket-like; a missing shutdown must not
    # turn teardown into a spurious 500 on the hijacked connection
    try:
        up_sock.shutdown(socket.SHUT_RDWR)
    except (OSError, AttributeError):
        pass
    t.join(timeout=10)
