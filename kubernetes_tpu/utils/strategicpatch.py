"""Strategic merge patch — the 3-way merge behind kubectl apply.

Reference: pkg/util/strategicpatch/patch.go. Operates on wire-form dicts
(what the last-applied annotation stores). Semantics:

- maps merge recursively; a key present in `original` (the last applied
  config) but absent from `modified` (the new config) was deleted by the
  user and is removed from the result; keys only the live object carries
  (server-set: status, clusterIP, nodeName, uid...) are preserved
- lists of maps with a merge key (the reference's patchMergeKey struct
  tags: containers/env/volumes by name, ports by containerPort/port,
  volumeMounts by mountPath) merge element-wise by that key with the
  same ownership rule; all other lists are replaced atomically
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

# field name -> candidate merge keys, first present in the elements wins
# (ref: the patchMergeKey tags in pkg/api/v1/types.go)
MERGE_KEYS: Dict[str, Sequence[str]] = {
    "containers": ("name",),
    "env": ("name",),
    "ports": ("containerPort", "port"),
    "volumes": ("name",),
    "volumeMounts": ("mountPath",),
    "conditions": ("type",),
    "imagePullSecrets": ("name",),
}


def _merge_key_for(field: str, *lists: Sequence[Any]) -> Optional[str]:
    for candidate in MERGE_KEYS.get(field, ()):
        for lst in lists:
            for el in lst:
                if isinstance(el, dict) and candidate in el:
                    return candidate
    return None


def _is_map_list(value: Any) -> bool:
    return isinstance(value, list) and \
        all(isinstance(el, dict) for el in value) and bool(value)


def merge_maps(original: Dict, modified: Dict, current: Dict) -> Dict:
    """(ref: patch.go mergeMap, three-way)"""
    out = dict(current)
    # deletions: owned by the last applied config, dropped from the new
    for key in original:
        if key not in modified and key in out:
            del out[key]
    for key, mval in modified.items():
        oval = original.get(key)
        cval = out.get(key)
        if isinstance(mval, dict) and isinstance(cval, dict):
            out[key] = merge_maps(oval if isinstance(oval, dict) else {},
                                  mval, cval)
        elif (_is_map_list(mval) or _is_map_list(cval)) and \
                isinstance(mval, list) and isinstance(cval, list):
            out[key] = _merge_lists(
                key, oval if isinstance(oval, list) else [], mval, cval)
        else:
            out[key] = mval
    return out


def _merge_lists(field: str, original: List, modified: List,
                 current: List) -> List:
    """(ref: patch.go mergeSlice — patchMergeKey lists merge by element,
    the rest replace)"""
    mk = _merge_key_for(field, original, modified, current)
    if mk is None:
        return list(modified)
    cur_by = {el[mk]: el for el in current
              if isinstance(el, dict) and mk in el}
    orig_keys = {el[mk] for el in original
                 if isinstance(el, dict) and mk in el}
    orig_by = {el[mk]: el for el in original
               if isinstance(el, dict) and mk in el}
    out: List = []
    mod_keys = set()
    for el in modified:
        if not isinstance(el, dict) or mk not in el:
            out.append(el)
            continue
        k = el[mk]
        mod_keys.add(k)
        if k in cur_by:
            out.append(merge_maps(orig_by.get(k, {}), el, cur_by[k]))
        else:
            out.append(el)
    # elements only the live object has: server-set (or another owner's)
    # unless the last applied config owned them — then they're deletions
    for el in current:
        if not isinstance(el, dict) or mk not in el:
            continue
        k = el[mk]
        if k not in mod_keys and k not in orig_keys:
            out.append(el)
    return out


def three_way_merge(original: Dict, modified: Dict,
                    current: Dict) -> Dict:
    """kubectl apply's patch: original = last applied config, modified =
    the new config, current = the live object. Returns the object to
    write back: the user's intent applied over the live state with
    server-set fields intact (ref: patch.go CreateThreeWayMergePatch +
    StrategicMergePatch, fused — we write the merged object, not a
    patch document)."""
    return merge_maps(original or {}, modified or {}, current or {})
