"""Strategic merge patch — the 3-way merge behind kubectl apply.

Reference: pkg/util/strategicpatch/patch.go. Operates on wire-form dicts
(what the last-applied annotation stores). Semantics:

- maps merge recursively; a key present in `original` (the last applied
  config) but absent from `modified` (the new config) was deleted by the
  user and is removed from the result; keys only the live object carries
  (server-set: status, clusterIP, nodeName, uid...) are preserved
- lists of maps with a merge key (the reference's patchMergeKey struct
  tags: containers/env/volumes by name, ports by containerPort/port,
  volumeMounts by mountPath) merge element-wise by that key with the
  same ownership rule; all other lists are replaced atomically
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

# field name -> candidate merge keys, first present in the elements wins
# (ref: the patchMergeKey tags in pkg/api/v1/types.go)
MERGE_KEYS: Dict[str, Sequence[str]] = {
    "containers": ("name",),
    "env": ("name",),
    "ports": ("containerPort", "port"),
    "volumes": ("name",),
    "volumeMounts": ("mountPath",),
    "conditions": ("type",),
    "imagePullSecrets": ("name",),
}


def _merge_key_for(field: str, *lists: Sequence[Any]) -> Optional[str]:
    for candidate in MERGE_KEYS.get(field, ()):
        for lst in lists:
            for el in lst:
                if isinstance(el, dict) and candidate in el:
                    return candidate
    return None


def _is_map_list(value: Any) -> bool:
    return isinstance(value, list) and \
        all(isinstance(el, dict) for el in value) and bool(value)


def merge_maps(original: Dict, modified: Dict, current: Dict) -> Dict:
    """(ref: patch.go mergeMap, three-way)"""
    out = dict(current)
    # deletions: owned by the last applied config, dropped from the new
    for key in original:
        if key not in modified and key in out:
            del out[key]
    for key, mval in modified.items():
        oval = original.get(key)
        cval = out.get(key)
        if isinstance(mval, dict) and isinstance(cval, dict):
            out[key] = merge_maps(oval if isinstance(oval, dict) else {},
                                  mval, cval)
        elif (_is_map_list(mval) or _is_map_list(cval)) and \
                isinstance(mval, list) and isinstance(cval, list):
            out[key] = _merge_lists(
                key, oval if isinstance(oval, list) else [], mval, cval)
        else:
            out[key] = mval
    return out


def _merge_lists(field: str, original: List, modified: List,
                 current: List) -> List:
    """(ref: patch.go mergeSlice — patchMergeKey lists merge by element,
    the rest replace)"""
    mk = _merge_key_for(field, original, modified, current)
    if mk is None:
        return list(modified)
    cur_by = {el[mk]: el for el in current
              if isinstance(el, dict) and mk in el}
    orig_keys = {el[mk] for el in original
                 if isinstance(el, dict) and mk in el}
    orig_by = {el[mk]: el for el in original
               if isinstance(el, dict) and mk in el}
    out: List = []
    mod_keys = set()
    for el in modified:
        if not isinstance(el, dict) or mk not in el:
            out.append(el)
            continue
        k = el[mk]
        mod_keys.add(k)
        if k in cur_by:
            out.append(merge_maps(orig_by.get(k, {}), el, cur_by[k]))
        else:
            out.append(el)
    # elements only the live object has: server-set (or another owner's)
    # unless the last applied config owned them — then they're deletions
    for el in current:
        if not isinstance(el, dict) or mk not in el:
            continue
        k = el[mk]
        if k not in mod_keys and k not in orig_keys:
            out.append(el)
    return out


_DIRECTIVE = "$patch"  # patch.go directiveMarker


def strategic_patch(current: Dict, patch: Dict) -> Dict:
    """Two-way strategic merge — the apiserver's PATCH with
    application/strategic-merge-patch+json (ref: resthandler.go
    patchResource -> strategicpatch.StrategicMergePatch): explicit
    nulls delete, maps recurse, patchMergeKey lists merge by element,
    other lists replace wholesale. The patch.go directives are
    honored: a map carrying `"$patch": "replace"` replaces instead of
    merging, a map carrying `"$patch": "delete"` empties it (the
    reference's mergeMap returns an empty map), a keyed list element
    carrying `"$patch": "delete"` removes its counterpart, and any
    OTHER directive value raises ValueError (mergeMap's "Unknown patch
    type" error — the apiserver surfaces it as a 400); directive
    markers never persist."""
    directive = patch.get(_DIRECTIVE)
    if directive == "replace":
        return {k: v for k, v in patch.items() if k != _DIRECTIVE}
    if directive == "delete":
        return {}
    if directive is not None:
        raise ValueError(
            f"unknown patch type: {directive!r} in map {patch!r}")
    out = dict(current)
    for key, pval in patch.items():
        if key == _DIRECTIVE:
            continue
        if pval is None:
            out.pop(key, None)
            continue
        cval = out.get(key)
        if isinstance(pval, dict):
            # merge against {} when the live key is absent/non-map so
            # directive markers strip either way
            out[key] = strategic_patch(
                cval if isinstance(cval, dict) else {}, pval)
        elif isinstance(pval, list) and _is_map_list(pval):
            # merge against [] when the live key is absent/non-list so
            # $patch markers strip either way
            out[key] = _merge_lists_two_way(
                key, pval, cval if isinstance(cval, list) else [])
        elif isinstance(pval, list) and isinstance(cval, list) \
                and _is_map_list(cval):
            out[key] = _merge_lists_two_way(key, pval, cval)
        else:
            out[key] = pval
    return out


def _strip_directives(el: Any) -> Any:
    if isinstance(el, dict):
        return {k: v for k, v in el.items() if k != _DIRECTIVE}
    return el


def _merge_lists_two_way(field: str, patch_list: List,
                         current: List) -> List:
    # a standalone {"$patch": "replace"} element (patch.go's
    # replace-list directive): the remaining elements ARE the new list
    if any(isinstance(el, dict) and el.get(_DIRECTIVE) == "replace"
           for el in patch_list):
        # the remaining (marker-stripped) elements ARE the new list;
        # the standalone {"$patch": "replace"} element itself drops
        return [_strip_directives(el) for el in patch_list
                if not (isinstance(el, dict)
                        and el.get(_DIRECTIVE) == "replace"
                        and len(el) == 1)]
    mk = _merge_key_for(field, patch_list, current)
    if mk is None or any(not isinstance(el, dict) or mk not in el
                         for el in patch_list):
        # unkeyed patch elements: replace (markers never persist)
        return [_strip_directives(el) for el in patch_list]
    deletes = {el[mk] for el in patch_list
               if el.get(_DIRECTIVE) == "delete"}
    patch_by = {el[mk]: el for el in patch_list
                if el[mk] not in deletes}
    out: List = []
    seen = set()
    for el in current:
        k = el.get(mk) if isinstance(el, dict) else None
        if k in deletes:
            continue  # "$patch": "delete" removes the counterpart
        if k in patch_by:
            seen.add(k)
            out.append(strategic_patch(el, patch_by[k]))
        else:
            out.append(el)
    for el in patch_list:
        if el[mk] not in seen and el[mk] not in deletes:
            out.append({k: v for k, v in el.items() if k != _DIRECTIVE})
    return out


def json_merge_patch(current: Any, patch: Any) -> Any:
    """RFC 7386 merge patch — application/merge-patch+json: null
    deletes, objects merge recursively, everything else (lists
    included) replaces."""
    if not isinstance(patch, dict):
        return patch
    out = dict(current) if isinstance(current, dict) else {}
    for k, v in patch.items():
        if v is None:
            out.pop(k, None)
        else:
            out[k] = json_merge_patch(out.get(k), v)
    return out


def _list_index(token: str) -> int:
    """RFC 6901 array token: non-negative digits, no leading zeros
    (negative Python indexing would silently target the wrong
    element)."""
    if not token.isdigit() or (len(token) > 1 and token[0] == "0"):
        raise ValueError(f"invalid array index {token!r}")
    return int(token)


def _pointer_walk(doc: Any, pointer: str):
    """RFC 6901: -> (parent, final token). '' addresses the root
    (parent None)."""
    if pointer == "":
        return None, None
    if not pointer.startswith("/"):
        raise ValueError(f"invalid JSON pointer {pointer!r}")
    tokens = [t.replace("~1", "/").replace("~0", "~")
              for t in pointer[1:].split("/")]
    cur = doc
    for t in tokens[:-1]:
        if isinstance(cur, list):
            cur = cur[_list_index(t)]
        elif isinstance(cur, dict):
            cur = cur[t]
        else:
            raise ValueError(f"pointer {pointer!r}: cannot traverse "
                             f"{type(cur).__name__}")
    return cur, tokens[-1]


def apply_json_patch(doc: Any, ops: List[Dict]) -> Any:
    """RFC 6902 — application/json-patch+json: add / remove / replace /
    move / copy / test over JSON pointers. Operates on (and returns) a
    deep copy; a failed `test` or bad pointer raises ValueError."""
    import copy
    import json as _json
    doc = copy.deepcopy(doc)
    for op in ops:
        if not isinstance(op, dict) or "path" not in op:
            raise ValueError("json-patch op missing required 'path'")
        kind = op.get("op")
        parent, tok = _pointer_walk(doc, op["path"])

        def _get(p, t):
            if isinstance(p, list):
                return p[_list_index(t)]
            if isinstance(p, dict):
                return p[t]
            raise ValueError(
                f"cannot index into {type(p).__name__} with {t!r}")

        if kind == "add":
            val = op["value"]
            if parent is None:
                doc = val
            elif isinstance(parent, list):
                i = len(parent) if tok == "-" else _list_index(tok)
                if i > len(parent):  # RFC 6902: > length is an error
                    raise ValueError(
                        f"add: index {i} beyond array length")
                parent.insert(i, val)
            else:
                parent[tok] = val
        elif kind == "remove":
            if parent is None:
                raise ValueError("cannot remove the root")
            if isinstance(parent, list):
                del parent[_list_index(tok)]
            else:
                del parent[tok]
        elif kind == "replace":
            if parent is None:
                doc = op["value"]
            elif isinstance(parent, list):
                parent[_list_index(tok)] = op["value"]
            else:
                if tok not in parent:
                    raise ValueError(f"replace: no member {tok!r}")
                parent[tok] = op["value"]
        elif kind in ("move", "copy"):
            src_parent, src_tok = _pointer_walk(doc, op["from"])
            val = doc if src_parent is None else _get(src_parent, src_tok)
            val = copy.deepcopy(val)
            if kind == "move":
                if isinstance(src_parent, list):
                    del src_parent[_list_index(src_tok)]
                elif src_parent is not None:
                    del src_parent[src_tok]
            # re-resolve: a move may have shifted list indices
            parent, tok = _pointer_walk(doc, op["path"])
            if parent is None:
                doc = val
            elif isinstance(parent, list):
                i = len(parent) if tok == "-" else _list_index(tok)
                if i > len(parent):  # RFC 6902: > length is an error
                    raise ValueError(
                        f"{kind}: index {i} beyond array length")
                parent.insert(i, val)
            else:
                parent[tok] = val
        elif kind == "test":
            have = doc if parent is None else _get(parent, tok)
            if _json.dumps(have, sort_keys=True) != \
                    _json.dumps(op["value"], sort_keys=True):
                raise ValueError(f"test failed at {op.get('path')!r}")
        else:
            raise ValueError(f"unknown json-patch op {kind!r}")
    return doc


def three_way_merge(original: Dict, modified: Dict,
                    current: Dict) -> Dict:
    """kubectl apply's patch: original = last applied config, modified =
    the new config, current = the live object. Returns the object to
    write back: the user's intent applied over the live state with
    server-set fields intact (ref: patch.go CreateThreeWayMergePatch +
    StrategicMergePatch, fused — we write the merged object, not a
    patch document)."""
    return merge_maps(original or {}, modified or {}, current or {})
