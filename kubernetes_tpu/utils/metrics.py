"""Minimal Prometheus-style metrics: counters, gauges, summaries.

Reference: the per-binary prometheus registries (pkg/apiserver/metrics,
plugin/pkg/scheduler/metrics/metrics.go:30-80, pkg/kubelet/metrics) exposed
on /metrics. We keep the same metric names so dashboards line up.
"""

from __future__ import annotations

import bisect
import threading
from collections import defaultdict, deque
from typing import Dict, List, Optional, Tuple


def _key(labels: Optional[Dict[str, str]]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((labels or {}).items()))


def _fmt_labels(k: Tuple[Tuple[str, str], ...]) -> str:
    if not k:
        return ""
    inner = ",".join(f'{name}="{val}"' for name, val in k)
    return "{" + inner + "}"


class _Summary:
    """Sliding-window summary: count, sum, and quantiles over the last N
    observations (enough for the 50th/90th/99th the SLO checks read)."""

    def __init__(self, max_samples: int = 10_000):
        self.count = 0
        self.total = 0.0
        self._samples: List[float] = []   # kept sorted for quantiles
        self._order: deque = deque()      # insertion order for eviction
        self._max = max_samples

    def observe(self, v: float) -> None:
        self.count += 1
        self.total += v
        if len(self._order) >= self._max:
            oldest = self._order.popleft()
            idx = bisect.bisect_left(self._samples, oldest)
            del self._samples[idx]
        self._order.append(v)
        bisect.insort(self._samples, v)

    def quantile(self, q: float) -> float:
        if not self._samples:
            return 0.0
        idx = min(len(self._samples) - 1, int(q * len(self._samples)))
        return self._samples[idx]


class MetricsRegistry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, Dict[tuple, float]] = defaultdict(dict)
        self._gauges: Dict[str, Dict[tuple, float]] = defaultdict(dict)
        self._summaries: Dict[str, Dict[tuple, _Summary]] = defaultdict(dict)

    def inc(self, name: str, labels: Optional[Dict[str, str]] = None,
            by: float = 1.0) -> None:
        k = _key(labels)
        with self._lock:
            self._counters[name][k] = self._counters[name].get(k, 0.0) + by

    def set_gauge(self, name: str, value: float,
                  labels: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            self._gauges[name][_key(labels)] = value

    def observe(self, name: str, value: float,
                labels: Optional[Dict[str, str]] = None) -> None:
        k = _key(labels)
        with self._lock:
            s = self._summaries[name].get(k)
            if s is None:
                s = self._summaries[name][k] = _Summary()
            s.observe(value)

    def summary_samples(self, name: str) -> Dict[tuple, List[float]]:
        """-> {labels_key: sorted sample window} — lets a reader merge
        windows across label sets for an all-traffic percentile (label
        summaries cannot be merged from quantiles alone)."""
        with self._lock:
            return {k: list(s._samples)
                    for k, s in self._summaries.get(name, {}).items()}

    def summary_stats(self, name: str
                      ) -> Dict[Tuple[Tuple[str, str], ...],
                                Dict[str, float]]:
        """-> {labels_key_tuple: {count, sum, p50, p90, p99}} for
        one summary metric — the server-side read the SLO suite gates
        on (the reference gates on apiserver metrics, not client
        probes: test/e2e/metrics_util.go:194-200)."""
        out = {}
        with self._lock:
            for k, s in self._summaries.get(name, {}).items():
                out[k] = {"count": s.count, "sum": s.total,
                          "p50": s.quantile(0.50),
                          "p90": s.quantile(0.90),
                          "p99": s.quantile(0.99)}
        return out

    # ---------------------------------------------------------------- read

    def counter(self, name: str, labels: Optional[Dict[str, str]] = None) -> float:
        with self._lock:
            return self._counters.get(name, {}).get(_key(labels), 0.0)

    def counter_sum(self, name: str) -> float:
        """Total across every label set of one counter — what a gate
        asserts when it cares that the thing happened, not which label
        it happened under (the crash soak's durability counters)."""
        with self._lock:
            return sum(self._counters.get(name, {}).values())

    def summary(self, name: str,
                labels: Optional[Dict[str, str]] = None) -> Optional[_Summary]:
        with self._lock:
            return self._summaries.get(name, {}).get(_key(labels))

    def render(self) -> str:
        """Prometheus text exposition format."""
        out: List[str] = []
        with self._lock:
            for name in sorted(self._counters):
                out.append(f"# TYPE {name} counter")
                for k, v in sorted(self._counters[name].items()):
                    out.append(f"{name}{_fmt_labels(k)} {v}")
            for name in sorted(self._gauges):
                out.append(f"# TYPE {name} gauge")
                for k, v in sorted(self._gauges[name].items()):
                    out.append(f"{name}{_fmt_labels(k)} {v}")
            for name in sorted(self._summaries):
                out.append(f"# TYPE {name} summary")
                for k, s in sorted(self._summaries[name].items()):
                    for q in (0.5, 0.9, 0.99):
                        lbl = dict(k); lbl["quantile"] = str(q)
                        out.append(f"{name}{_fmt_labels(_key(lbl))} {s.quantile(q)}")
                    out.append(f"{name}_sum{_fmt_labels(k)} {s.total}")
                    out.append(f"{name}_count{_fmt_labels(k)} {s.count}")
        return "\n".join(out) + "\n"


#: shared default registry (each binary may still make its own)
global_metrics = MetricsRegistry()

#: Durability / HA counters: wal_* incremented by core/wal.py and the
#: store recovery paths, leader/lease ones by utils/leaderelection.py.
#: The crash-soak gates (tests/test_chaos.py) assert these move; the
#: names are pinned here so dashboards and gates cannot drift.
DURABILITY_COUNTERS = (
    "wal_records_total",        # ledger records appended to the WAL
    "wal_snapshots_total",      # snapshot compactions written
    "wal_recoveries_total",     # Store/NativeStore.recover completions
    "leader_transitions_total", # elector acquisitions (label: name)
    "lease_renew_failures_total",  # failed renew attempts (label: name)
)

#: Pod-lifecycle stage model (the obs tracing layer): every span that
#: carries a stage tag lands one observation in this summary, so
#: render() exposes the spans-derived decomposition under ONE stable
#: metric name — {stage=...} label values are pinned below (no-drift,
#: like DURABILITY_COUNTERS; bench.py's obs section and the stage
#: glossary in README both read these names).
OBS_STAGE_SUMMARY = "pod_e2e_stage_seconds"

#: where a pod's wall-clock goes, create -> kubelet confirm:
OBS_STAGES = (
    "create",    # apiserver/registry create commit (server-side)
    "queue",     # pending FIFO wait: informer delivery -> tile drain
    "schedule",  # tile snapshot/encode up to device dispatch
    "device",    # device execute: dispatch -> assignments materialized
    "bind",      # bind txn: CAS commit of a tile's bindings
    "publish",   # store publish fan-out to watchers
    "confirm",   # kubelet confirm: fleet status batch -> committed
)

#: Workload-replay counters: incremented by the controllers the
#: trace-replay soak shakes out; pinned here for the same no-drift
#: reason as DURABILITY_COUNTERS (the workload gates assert these).
WORKLOAD_COUNTERS = (
    "job_backoff_requeues_total",  # Job syncs held back by failure
                                   # backoff (label: job)
)
