"""Minimal Prometheus-style metrics: counters, gauges, summaries,
histograms.

Reference: the per-binary prometheus registries (pkg/apiserver/metrics,
plugin/pkg/scheduler/metrics/metrics.go:30-80, pkg/kubelet/metrics) exposed
on /metrics. We keep the same metric names so dashboards line up.

Summaries hold a sliding sample window and answer quantiles for ONE
process's ONE label set; they cannot be merged (a p99 of p99s is not a
p99). Histograms hold counts in pinned buckets, so two histograms with
the same boundaries merge by adding counts — across label sets, across
processes, across scrape rounds. That is why the fleet scraper
(obs/metricsplane.py) aggregates histograms, and why the bucket
boundaries are pinned HERE per metric name (HISTOGRAM_BUCKETS): two
registries that disagreed on boundaries would be unmergeable.
"""

from __future__ import annotations

import bisect
import threading
from collections import defaultdict, deque
from typing import Dict, List, Optional, Tuple


def _key(labels: Optional[Dict[str, str]]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((labels or {}).items()))


def escape_label_value(val: str) -> str:
    """Prometheus text-format label-value escaping: backslash first
    (the escape character itself), then quote and newline — the three
    characters the exposition format reserves."""
    return (str(val).replace("\\", "\\\\")
                    .replace('"', '\\"')
                    .replace("\n", "\\n"))


def _fmt_labels(k: Tuple[Tuple[str, str], ...]) -> str:
    if not k:
        return ""
    inner = ",".join(f'{name}="{escape_label_value(val)}"'
                     for name, val in k)
    return "{" + inner + "}"


def _fmt_le(bound: float) -> str:
    """Bucket upper-bound label value: '+Inf' for the overflow bucket,
    otherwise Python's shortest round-trip float repr (byte-stable
    across runs, exact through the scrape parser)."""
    if bound == float("inf"):
        return "+Inf"
    return repr(float(bound))


class _Summary:
    """Sliding-window summary: count, sum, and quantiles over the last N
    observations (enough for the 50th/90th/99th the SLO checks read)."""

    def __init__(self, max_samples: int = 10_000):
        self.count = 0
        self.total = 0.0
        self._samples: List[float] = []   # kept sorted for quantiles
        self._order: deque = deque()      # insertion order for eviction
        self._max = max_samples

    def observe(self, v: float) -> None:
        self.count += 1
        self.total += v
        if len(self._order) >= self._max:
            oldest = self._order.popleft()
            idx = bisect.bisect_left(self._samples, oldest)
            del self._samples[idx]
        self._order.append(v)
        bisect.insort(self._samples, v)

    def quantile(self, q: float) -> float:
        if not self._samples:
            return 0.0
        idx = min(len(self._samples) - 1, int(q * len(self._samples)))
        return self._samples[idx]


class Histogram:
    """Cumulative-bucket histogram over pinned boundaries.

    Buckets are per-observation counts keyed by upper bound; the +Inf
    overflow bucket is implicit (counts[-1]). Unlike _Summary this is
    a pure monoid: merge() of two histograms with identical bounds is
    exact, associative, and commutative — the property the fleet
    scraper leans on to fold per-process /metrics into one view.
    """

    __slots__ = ("bounds", "counts", "total", "count")

    def __init__(self, bounds: Tuple[float, ...]):
        if tuple(bounds) != tuple(sorted(bounds)) or not bounds:
            raise ValueError(f"bucket bounds must be sorted, non-empty: "
                             f"{bounds!r}")
        self.bounds: Tuple[float, ...] = tuple(float(b) for b in bounds)
        self.counts: List[int] = [0] * (len(self.bounds) + 1)  # +Inf last
        self.total = 0.0
        self.count = 0

    def observe(self, v: float) -> None:
        # le is inclusive: v lands in the first bucket whose bound >= v
        self.counts[bisect.bisect_left(self.bounds, v)] += 1
        self.total += v
        self.count += 1

    def merge(self, other: "Histogram") -> "Histogram":
        """-> new Histogram = self + other (bounds must match)."""
        if self.bounds != other.bounds:
            raise ValueError(
                f"unmergeable histograms: bounds {self.bounds} != "
                f"{other.bounds}")
        out = Histogram(self.bounds)
        out.counts = [a + b for a, b in zip(self.counts, other.counts)]
        out.total = self.total + other.total
        out.count = self.count + other.count
        return out

    def cumulative(self) -> List[int]:
        """Per-bucket counts folded into the cumulative counts the
        _bucket{le=} exposition lines carry (last == count)."""
        out, run = [], 0
        for c in self.counts:
            run += c
            out.append(run)
        return out

    def quantile_le(self, le: float) -> int:
        """Observations <= le, for any le that is a pinned bound —
        what a latency SLO reads as its 'good events' counter."""
        idx = bisect.bisect_left(self.bounds, le)
        if idx >= len(self.bounds) or self.bounds[idx] != le:
            raise ValueError(f"le={le} is not a pinned bound of "
                             f"{self.bounds}")
        return sum(self.counts[:idx + 1])

    def to_dict(self) -> dict:
        return {"bounds": list(self.bounds), "counts": list(self.counts),
                "sum": self.total, "count": self.count}

    @classmethod
    def from_dict(cls, d: dict) -> "Histogram":
        h = cls(tuple(d["bounds"]))
        h.counts = [int(c) for c in d["counts"]]
        h.total = float(d["sum"])
        h.count = int(d["count"])
        return h


class MetricsRegistry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, Dict[tuple, float]] = defaultdict(dict)
        self._gauges: Dict[str, Dict[tuple, float]] = defaultdict(dict)
        self._summaries: Dict[str, Dict[tuple, _Summary]] = defaultdict(dict)
        self._histograms: Dict[str, Dict[tuple, Histogram]] = \
            defaultdict(dict)

    def inc(self, name: str, labels: Optional[Dict[str, str]] = None,
            by: float = 1.0) -> None:
        k = _key(labels)
        with self._lock:
            self._counters[name][k] = self._counters[name].get(k, 0.0) + by

    def set_gauge(self, name: str, value: float,
                  labels: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            self._gauges[name][_key(labels)] = value

    def observe(self, name: str, value: float,
                labels: Optional[Dict[str, str]] = None) -> None:
        k = _key(labels)
        with self._lock:
            s = self._summaries[name].get(k)
            if s is None:
                s = self._summaries[name][k] = _Summary()
            s.observe(value)
            # dual-landing: any metric with pinned boundaries also
            # feeds a histogram, so the hot-path call sites (tracer
            # stage ends, apiserver service time, watch publish lag)
            # grow a mergeable cross-process view without touching
            # any call site
            bounds = HISTOGRAM_BUCKETS.get(name)
            if bounds is not None:
                h = self._histograms[name].get(k)
                if h is None:
                    h = self._histograms[name][k] = Histogram(bounds)
                h.observe(value)

    def observe_histogram(self, name: str, value: float,
                          labels: Optional[Dict[str, str]] = None) -> None:
        """Histogram-only observation (no summary window). The bucket
        boundaries MUST be pinned in HISTOGRAM_BUCKETS — an unpinned
        name would mint boundaries nobody else can merge with."""
        bounds = HISTOGRAM_BUCKETS.get(name)
        if bounds is None:
            raise ValueError(
                f"histogram {name!r} has no pinned boundaries in "
                f"utils.metrics.HISTOGRAM_BUCKETS")
        k = _key(labels)
        with self._lock:
            h = self._histograms[name].get(k)
            if h is None:
                h = self._histograms[name][k] = Histogram(bounds)
            h.observe(value)

    def summary_samples(self, name: str) -> Dict[tuple, List[float]]:
        """-> {labels_key: sorted sample window} — lets a reader merge
        windows across label sets for an all-traffic percentile (label
        summaries cannot be merged from quantiles alone)."""
        with self._lock:
            return {k: list(s._samples)
                    for k, s in self._summaries.get(name, {}).items()}

    def summary_stats(self, name: str
                      ) -> Dict[Tuple[Tuple[str, str], ...],
                                Dict[str, float]]:
        """-> {labels_key_tuple: {count, sum, p50, p90, p99}} for
        one summary metric — the server-side read the SLO suite gates
        on (the reference gates on apiserver metrics, not client
        probes: test/e2e/metrics_util.go:194-200)."""
        out = {}
        with self._lock:
            for k, s in self._summaries.get(name, {}).items():
                out[k] = {"count": s.count, "sum": s.total,
                          "p50": s.quantile(0.50),
                          "p90": s.quantile(0.90),
                          "p99": s.quantile(0.99)}
        return out

    # ---------------------------------------------------------------- read

    def counter(self, name: str, labels: Optional[Dict[str, str]] = None) -> float:
        with self._lock:
            return self._counters.get(name, {}).get(_key(labels), 0.0)

    def gauge(self, name: str,
              labels: Optional[Dict[str, str]] = None) -> Optional[float]:
        """Last value set_gauge recorded for one label set (None if the
        gauge was never set) — what a bench reads back for a depth
        gauge like watch_fanout_queue_depth."""
        with self._lock:
            return self._gauges.get(name, {}).get(_key(labels))

    def counter_sum(self, name: str) -> float:
        """Total across every label set of one counter — what a gate
        asserts when it cares that the thing happened, not which label
        it happened under (the crash soak's durability counters)."""
        with self._lock:
            return sum(self._counters.get(name, {}).values())

    def summary(self, name: str,
                labels: Optional[Dict[str, str]] = None) -> Optional[_Summary]:
        with self._lock:
            return self._summaries.get(name, {}).get(_key(labels))

    def histogram(self, name: str,
                  labels: Optional[Dict[str, str]] = None
                  ) -> Optional[Histogram]:
        """Snapshot copy of one histogram (safe to merge/read outside
        the registry lock)."""
        with self._lock:
            h = self._histograms.get(name, {}).get(_key(labels))
            return Histogram.from_dict(h.to_dict()) if h else None

    def histogram_merged(self, name: str) -> Optional[Histogram]:
        """One histogram folded across every label set — the exact
        merge summaries cannot do (an all-traffic latency view)."""
        with self._lock:
            hists = list(self._histograms.get(name, {}).values())
            if not hists:
                return None
            out = Histogram(hists[0].bounds)
            for h in hists:
                out = out.merge(h)
        return out

    def histogram_stats(self, name: str
                        ) -> Dict[Tuple[Tuple[str, str], ...], dict]:
        """-> {labels_key: Histogram.to_dict()} for one histogram."""
        with self._lock:
            return {k: h.to_dict()
                    for k, h in self._histograms.get(name, {}).items()}

    def render(self) -> str:
        """Prometheus text exposition format."""
        out: List[str] = []
        with self._lock:
            for name in sorted(self._counters):
                out.append(f"# TYPE {name} counter")
                for k, v in sorted(self._counters[name].items()):
                    out.append(f"{name}{_fmt_labels(k)} {v}")
            for name in sorted(self._gauges):
                out.append(f"# TYPE {name} gauge")
                for k, v in sorted(self._gauges[name].items()):
                    out.append(f"{name}{_fmt_labels(k)} {v}")
            for name in sorted(self._summaries):
                out.append(f"# TYPE {name} summary")
                for k, s in sorted(self._summaries[name].items()):
                    for q in (0.5, 0.9, 0.99):
                        lbl = dict(k); lbl["quantile"] = str(q)
                        out.append(f"{name}{_fmt_labels(_key(lbl))} {s.quantile(q)}")
                    out.append(f"{name}_sum{_fmt_labels(k)} {s.total}")
                    out.append(f"{name}_count{_fmt_labels(k)} {s.count}")
            for name in sorted(self._histograms):
                out.append(f"# TYPE {name} histogram")
                for k, h in sorted(self._histograms[name].items()):
                    cum = h.cumulative()
                    for bound, c in zip(h.bounds + (float("inf"),), cum):
                        lbl = dict(k); lbl["le"] = _fmt_le(bound)
                        out.append(
                            f"{name}_bucket{_fmt_labels(_key(lbl))} {c}")
                    out.append(f"{name}_sum{_fmt_labels(k)} {h.total}")
                    out.append(f"{name}_count{_fmt_labels(k)} {h.count}")
        return "\n".join(out) + "\n"


#: shared default registry (each binary may still make its own)
global_metrics = MetricsRegistry()

#: Durability / HA counters: wal_* incremented by core/wal.py and the
#: store recovery paths, leader/lease ones by utils/leaderelection.py.
#: The crash-soak gates (tests/test_chaos.py) assert these move; the
#: names are pinned here so dashboards and gates cannot drift.
DURABILITY_COUNTERS = (
    "wal_records_total",        # ledger records appended to the WAL
    "wal_snapshots_total",      # snapshot compactions written
    "wal_recoveries_total",     # Store/NativeStore.recover completions
    "leader_transitions_total", # elector acquisitions (label: name)
    "lease_renew_failures_total",  # failed renew attempts (label: name)
)

#: Shard-failure counters (sched/device/shardfail.py): the shard-kill
#: soak (kubemark/shard_soak.py) gates on these moving, so the names
#: are pinned with the same no-drift contract as DURABILITY_COUNTERS.
SHARD_COUNTERS = (
    "shard_lease_transitions_total",  # dead-shard fencing takeovers
                                      # (label: lease) — the CAS that
                                      # advances lease_transitions
    "shard_reshards_total",           # survivor re-shards applied
    "shard_replay_rows_total",        # journaled rows replayed onto
                                      # survivors across all reshards
)

#: Pod-lifecycle stage model (the obs tracing layer): every span that
#: carries a stage tag lands one observation in this summary, so
#: render() exposes the spans-derived decomposition under ONE stable
#: metric name — {stage=...} label values are pinned below (no-drift,
#: like DURABILITY_COUNTERS; bench.py's obs section and the stage
#: glossary in README both read these names).
OBS_STAGE_SUMMARY = "pod_e2e_stage_seconds"

#: where a pod's wall-clock goes, create -> kubelet confirm:
OBS_STAGES = (
    "create",    # apiserver/registry create commit (server-side)
    "queue",     # pending FIFO wait: informer delivery -> tile drain
    "schedule",  # tile snapshot/encode up to device dispatch
    "device",    # device execute: dispatch -> assignments materialized
    "bind",      # bind txn: CAS commit of a tile's bindings
    "publish",   # store publish fan-out to watchers
    "confirm",   # kubelet confirm: fleet status batch -> committed
)

#: Workload-replay counters: incremented by the controllers the
#: trace-replay soak shakes out; pinned here for the same no-drift
#: reason as DURABILITY_COUNTERS (the workload gates assert these).
WORKLOAD_COUNTERS = (
    "job_backoff_requeues_total",  # Job syncs held back by failure
                                   # backoff (label: job)
)

#: Per-(verb, resource) apiserver service time in MICROSECONDS —
#: observed in ApiServer.handle's finally block; the density SLO suite
#: and the burn-rate evaluator both read this name (was a stray
#: literal in kubemark/slo.py before the no-drift contract landed).
APISERVER_LATENCY_SUMMARY = "apiserver_request_latencies_microseconds"

#: Watch publish -> deliver lag in SECONDS: stamped when a commit's
#: events enter the store publish ring, observed when a consumer's
#: drain hands them to watcher fan-out (core/store.py). The default
#: committer-drained shard observes unlabeled; worker fan-out shards
#: observe with {shard=...} (burn-rate evaluation sums label sets).
WATCH_LAG_HISTOGRAM = "watch_publish_deliver_lag_seconds"

#: Publish-ring backlog per fan-out shard (pub_seq head minus the
#: shard's delivery cursor), set by FanoutShard.drain with
#: {shard=...}. A shard stuck behind a slow fan-out shows here before
#: its watchers overrun and take the 410 path.
FANOUT_QUEUE_DEPTH_GAUGE = "watch_fanout_queue_depth"

#: Requests served per apiserver worker (label: worker). The serving
#: bench and fanout soak read this to show spread across the pool.
APISERVER_WORKER_REQUESTS = "apiserver_worker_requests"

#: Flash-crowd progress counters the workload soak's burn-rate SLO
#: reads: created is incremented synchronously at crowd injection,
#: bound when the tracker sees the crowd pod bind. error ratio =
#: 1 - d(bound)/d(created) over a sample window.
CROWD_COUNTERS = (
    "crowd_pods_created_total",
    "crowd_pods_bound_total",
)

#: Scraper-side bookkeeping (obs/metricsplane.py): counter resets seen
#: while folding per-target samples (a crashed+restarted process's
#: counters restart at 0; the scraper rebases so rates never go
#: negative) and scrape errors (target unreachable that round).
SCRAPE_COUNTERS = (
    "scrape_counter_resets_total",
    "scrape_errors_total",
)

#: Priority-preemption counters (sched/batch.py _try_preempt + the
#: flash-drain soak's post-hoc oracle audit): attempts counts victim
#: searches run, victims counts uid-preconditioned evictions issued,
#: wrongful counts audit violations — the soak gates on wrongful == 0.
PREEMPTION_COUNTERS = (
    "preemption_attempts_total",
    "preemption_victims_total",
    "preemption_wrongful_total",
)

#: Surge progress counters the flash-drain soak's burn-rate SLO reads
#: (same shape as CROWD_COUNTERS): created is incremented synchronously
#: at surge injection, bound_fast when the tracker sees the surge pod
#: bind within the fast-bind limit.
SURGE_COUNTERS = (
    "surge_pods_created_total",
    "surge_pods_bound_fast_total",
)

#: Surge bind latency (injection -> observed binding), seconds.
SURGE_BIND_HISTOGRAM = "preemption_surge_bind_seconds"

#: Pinned per-metric histogram bucket boundaries. observe() dual-lands
#: any of these names into a Histogram next to its summary; boundaries
#: live HERE (not at call sites) because merging across processes
#: requires every registry to agree on them. Units follow the metric
#: name suffix.
HISTOGRAM_BUCKETS: Dict[str, Tuple[float, ...]] = {
    # stage seconds: sub-ms ledger commits up to multi-second confirms
    OBS_STAGE_SUMMARY: (
        0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
        0.25, 0.5, 1.0, 2.5, 5.0, 10.0),
    # apiserver service time, microseconds (ref gate: p99 < 1s = 1e6us)
    APISERVER_LATENCY_SUMMARY: (
        100.0, 250.0, 500.0, 1000.0, 2500.0, 5000.0, 10000.0,
        25000.0, 50000.0, 100000.0, 250000.0, 500000.0,
        1000000.0, 2500000.0),
    # watch publish lag, seconds: fan-out normally drains sub-ms; the
    # 5/10s tail buckets exist for the 10k-watcher fan-out storm
    # (a GIL-bound worker pump behind 10k sends can stall whole
    # seconds — the SLO needs to see that tail, not clip it)
    WATCH_LAG_HISTOGRAM: (
        0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
        0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0),
    # surge bind latency, seconds: the 5s bucket edge is the soak's
    # fast-bind limit (a preempted-then-bound surge pod pays victim
    # grace + one requeue round trip, normally well under it)
    SURGE_BIND_HISTOGRAM: (
        0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0),
}
