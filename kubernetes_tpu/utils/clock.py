"""Real and fake clocks (ref: pkg/util/clock.go — the fake clock is what
makes eviction/backoff logic unit-testable without sleeping)."""

from __future__ import annotations

import threading
import time


class Clock:
    def now(self) -> float:
        raise NotImplementedError

    def sleep(self, seconds: float) -> None:
        raise NotImplementedError


class RealClock(Clock):
    def now(self) -> float:
        return time.time()

    def sleep(self, seconds: float) -> None:
        time.sleep(seconds)


class FakeClock(Clock):
    def __init__(self, start: float = 0.0):
        self._now = start
        self._cond = threading.Condition()

    def now(self) -> float:
        with self._cond:
            return self._now

    def sleep(self, seconds: float) -> None:
        target = self.now() + seconds
        with self._cond:
            while self._now < target:
                self._cond.wait(0.01)

    def step(self, seconds: float) -> None:
        with self._cond:
            self._now += seconds
            self._cond.notify_all()
