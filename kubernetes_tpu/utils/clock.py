"""Real and fake clocks (ref: pkg/util/clock.go — the fake clock is what
makes eviction/backoff logic unit-testable without sleeping).

Two time axes: now() is WALL time (timestamps on API objects, TTL
deadlines) and monotonic() is a jump-free axis for deadlines and
leases. Leader election runs entirely on monotonic() — a backwards
wall-clock step (NTP correction, VM migration) must neither drop nor
extend leadership (tests/test_leaderelection.py pins this). FakeClock
keeps the axes separable: step() advances both, jump_wall() skews only
the wall clock, exactly the failure being regression-tested.
"""

from __future__ import annotations

import threading
import time


class Clock:
    def now(self) -> float:
        raise NotImplementedError

    def monotonic(self) -> float:
        raise NotImplementedError

    def sleep(self, seconds: float) -> None:
        raise NotImplementedError


class RealClock(Clock):
    def now(self) -> float:
        return time.time()

    def monotonic(self) -> float:
        return time.monotonic()

    def sleep(self, seconds: float) -> None:
        time.sleep(seconds)


#: the process-wide real clock. Stateless, so one shared instance is
#: enough — components take `clock: Clock = REAL` and tests hand in a
#: FakeClock. Direct `time.time()` in seeded/replayed code is a lint
#: error (kubernetes_tpu/lint, "determinism" rule); this singleton is
#: the sanctioned default.
REAL = RealClock()


class FakeClock(Clock):
    def __init__(self, start: float = 0.0):
        self._now = start          # the monotonic axis
        self._wall_offset = 0.0    # wall = monotonic + offset
        self._cond = threading.Condition()

    def now(self) -> float:
        with self._cond:
            return self._now + self._wall_offset

    def monotonic(self) -> float:
        with self._cond:
            return self._now

    def sleep(self, seconds: float) -> None:
        target = self.monotonic() + seconds
        with self._cond:
            while self._now < target:
                self._cond.wait(0.01)

    def step(self, seconds: float) -> None:
        """Advance TIME (both axes) — the normal passage of seconds."""
        with self._cond:
            self._now += seconds
            self._cond.notify_all()

    def jump_wall(self, seconds: float) -> None:
        """Skew the WALL clock only (negative = backwards NTP step).
        Monotonic readers must be unaffected."""
        with self._cond:
            self._wall_offset += seconds
            self._cond.notify_all()
