from .clock import Clock, FakeClock, RealClock
from .metrics import MetricsRegistry, global_metrics
from .trace import Trace
