"""Lease-based leader election over the leases resource.

Reference: the v1.1 reference elects its master through a raw
etcd compare-and-swap seam (the "master election" TODO around
cmd/kube-controller-manager); the later reference grew that seam into
client-go's tools/leaderelection over coordination/v1 Leases. This is
that design forward-ported: acquire/renew/release are CAS PUTs keyed
on the lease's resourceVersion, so two electors racing for the same
expired lease resolve to exactly one winner at the store.

Liveness is judged on each elector's LOCAL monotonic clock
(utils/clock.py monotonic()): an elector records WHEN it last saw the
lease record change (`_observed_at`) and treats the holder as live
until `observed_at + lease_duration` on that axis. The wall-clock
renewTime/acquireTime fields on the Lease are informational only — a
backwards time.time() step can neither drop nor extend leadership
(tests/test_leaderelection.py's wall-jump regression).

Fencing: `spec.lease_transitions` increments on every holder CHANGE
(never on renewal) — the term. At most one holder can exist per term,
because entering a term requires winning the CAS that increments it.
Downstream actors that must not act on behalf of a dead leader compare
terms (`elector.term`).

Metrics: `leader_transitions_total` on every acquisition,
`lease_renew_failures_total` on every failed renew attempt — both
asserted by the crash-soak gates (tests/test_chaos.py).
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass, field, replace
from typing import Any, Callable, Optional

from ..core import types as api
from ..core.errors import Conflict, NotFound
from .clock import Clock, RealClock
from .metrics import MetricsRegistry, global_metrics

logger = logging.getLogger(__name__)


@dataclass
class LeaderElectionConfig:
    """Timing knobs, with the reference's default proportions
    (leaderelection.go: 15s/10s/2s)."""
    lease_name: str
    identity: str
    namespace: str = "kube-system"
    #: how long a holder is presumed live after its last observed change
    lease_duration: float = 15.0
    #: a leader that cannot renew within this window of its last
    #: successful renewal steps down (must be < lease_duration, so the
    #: old leader demotes itself before a standby can win the lease)
    renew_deadline: float = 10.0
    #: how often candidates retry acquisition / leaders renew
    retry_period: float = 2.0
    clock: Clock = field(default_factory=RealClock)


class LeaderElector:
    """Acquire/renew/release a Lease via CAS; run callbacks on
    leadership transitions. One elector = one candidate process."""

    def __init__(self, client, config: LeaderElectionConfig,
                 on_started_leading: Optional[Callable[[int], None]] = None,
                 on_stopped_leading: Optional[Callable[[], None]] = None,
                 metrics: Optional[MetricsRegistry] = None):
        self.client = client
        self.config = config
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self.metrics = metrics or global_metrics
        self._leading = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        #: fencing term of the CURRENT (or last) leadership session
        self.term = 0
        # what this elector last saw on the lease record, and WHEN on
        # its local monotonic clock — the only liveness authority
        self._observed_rv = ""
        self._observed_holder = ""
        self._observed_at = 0.0

    # ------------------------------------------------------------ state

    @property
    def is_leader(self) -> bool:
        return self._leading.is_set()

    # ------------------------------------------------------- lease verbs

    def _observe(self, lease: api.Lease) -> None:
        """Track record changes; the observation clock only resets when
        the resourceVersion MOVES (a dead holder's unchanged record
        ages toward expiry no matter how often we re-read it)."""
        if lease.metadata.resource_version != self._observed_rv:
            self._observed_rv = lease.metadata.resource_version
            self._observed_holder = lease.spec.holder_identity
            self._observed_at = self.config.clock.monotonic()

    def try_acquire_or_renew(self) -> bool:
        """One CAS round: create the lease if absent, renew it if held
        by us, take it over if the holder's lease has expired on OUR
        monotonic clock. Returns True iff we hold the lease after the
        round. Any API failure or lost CAS returns False — the caller
        retries on its cadence."""
        c = self.config
        now_mono = c.clock.monotonic()
        wall = api.now_rfc3339()
        try:
            lease = self.client.get("leases", c.lease_name, c.namespace)
        except NotFound:
            fresh = api.Lease(
                metadata=api.ObjectMeta(name=c.lease_name,
                                        namespace=c.namespace),
                spec=api.LeaseSpec(
                    holder_identity=c.identity,
                    lease_duration_seconds=int(c.lease_duration),
                    acquire_time=wall, renew_time=wall,
                    lease_transitions=1))
            try:
                created = self.client.create("leases", fresh, c.namespace)
            except Exception:
                return False  # raced another creator (or API fault)
            self._observe(created)
            self.term = created.spec.lease_transitions
            return True
        except Exception:
            return False  # API fault: indistinguishable from a race
        self._observe(lease)
        held_by_us = lease.spec.holder_identity == c.identity
        if not held_by_us and lease.spec.holder_identity:
            if now_mono < self._observed_at + c.lease_duration:
                return False  # holder still presumed live
        spec_fields = dict(holder_identity=c.identity, renew_time=wall)
        if not held_by_us:
            # taking over: new term (fencing), fresh acquire stamp
            spec_fields["acquire_time"] = wall
            spec_fields["lease_transitions"] = \
                lease.spec.lease_transitions + 1
        updated = replace(lease, spec=replace(lease.spec, **spec_fields))
        try:
            # the PUT carries lease.metadata.resource_version: the
            # store's CAS picks exactly one winner among racers
            out = self.client.update("leases", updated, c.namespace)
        except Conflict:
            return False  # lost the race; re-observe next round
        except Exception:
            return False
        self._observe(out)
        self.term = out.spec.lease_transitions
        return True

    def release(self) -> None:
        """Clean handoff on voluntary shutdown: empty the holder so a
        standby acquires immediately instead of waiting out the lease.
        A crashed process never gets here — that's what expiry is for."""
        c = self.config
        try:
            lease = self.client.get("leases", c.lease_name, c.namespace)
            if lease.spec.holder_identity != c.identity:
                return
            self.client.update(
                "leases",
                replace(lease, spec=replace(lease.spec,
                                            holder_identity="")),
                c.namespace)
        except Exception:
            pass

    # -------------------------------------------------------------- run

    def run(self) -> "LeaderElector":
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"elector-{self.config.lease_name}-{self.config.identity}")
        self._thread.start()
        return self

    def stop(self, release: bool = True) -> None:
        """Voluntary shutdown: stop the loop, demote, optionally hand
        the lease off."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        if self._leading.is_set():
            self._demote()
        if release:
            self.release()

    def kill(self) -> None:
        """Simulated process death (chaos/crash.py): the loop stops and
        NO lease release happens — successors must wait out expiry and
        win the CAS, the same path a real crash leaves behind. The
        leading flag drops so a zombie component wired to is_leader
        stops acting, but on_stopped_leading does NOT run (a dead
        process runs nothing)."""
        self._stop.set()
        self._leading.clear()

    def _demote(self) -> None:
        self._leading.clear()
        if self.on_stopped_leading is not None:
            try:
                self.on_stopped_leading()
            except Exception:
                logger.exception("on_stopped_leading failed")

    def _run(self) -> None:
        c = self.config
        while not self._stop.is_set():
            # candidate phase
            while not self._stop.is_set():
                if self.try_acquire_or_renew():
                    break
                c.clock.sleep(c.retry_period)
            if self._stop.is_set():
                return
            self.metrics.inc("leader_transitions_total",
                             {"name": c.lease_name})
            self._leading.set()
            if self.on_started_leading is not None:
                try:
                    self.on_started_leading(self.term)
                except Exception:
                    logger.exception("on_started_leading failed")
            # leader phase: renew on the retry cadence; step down when
            # the last successful renewal ages past renew_deadline on
            # the monotonic clock
            last_renew = c.clock.monotonic()
            while not self._stop.is_set():
                c.clock.sleep(c.retry_period)
                if self._stop.is_set():
                    break
                if self.try_acquire_or_renew():
                    last_renew = c.clock.monotonic()
                else:
                    self.metrics.inc("lease_renew_failures_total",
                                     {"name": c.lease_name})
                    if (c.clock.monotonic() - last_renew
                            >= c.renew_deadline):
                        logger.warning(
                            "%s: lost leadership of %s (renew deadline)",
                            c.identity, c.lease_name)
                        self._demote()
                        break


def fence_lease(client, lease_name: str, identity: str,
                namespace: str = "kube-system") -> int:
    """One CAS takeover of a lease the caller has ALREADY judged
    expired on its own monotonic clock: write `identity` as holder and
    advance `lease_transitions` — the fencing term. The dead owner's
    next renew (if it resurrects) carries a stale resourceVersion and
    loses the CAS, so no action taken under the old term can land
    after this returns. Returns the new term; raises Conflict when the
    CAS loses (the holder renewed after all — NOT expired) and
    NotFound when the lease never existed.

    This is the reshard coordinator's half of the shard-lease protocol
    (sched/device/shardfail.py): shard owners run ordinary
    LeaderElectors, the coordinator fences a dead shard before
    re-sharding its slots onto the survivors."""
    lease = client.get("leases", lease_name, namespace)
    wall = api.now_rfc3339()
    updated = replace(lease, spec=replace(
        lease.spec, holder_identity=identity, acquire_time=wall,
        renew_time=wall,
        lease_transitions=lease.spec.lease_transitions + 1))
    out = client.update("leases", updated, namespace)
    return out.spec.lease_transitions
