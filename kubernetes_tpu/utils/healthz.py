"""Tiny healthz/metrics server every control-plane binary mounts.

Reference: pkg/healthz (235 LoC) + the per-binary mounts (scheduler
serves healthz/metrics/pprof on :10251,
plugin/cmd/kube-scheduler/app/server.go:128-143; controller-manager on
:10252). The componentstatus resource probes these fixed local ports
(pkg/registry/componentstatus)."""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from .metrics import MetricsRegistry, global_metrics

SCHEDULER_PORT = 10251            # ref: --port default, scheduler
CONTROLLER_MANAGER_PORT = 10252   # ref: --port default, controller-manager


class HealthzServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 metrics: Optional[MetricsRegistry] = None,
                 checks: Optional[dict] = None):
        """checks: name -> callable() raising/False on unhealthy."""
        self.metrics = metrics or global_metrics
        self.checks = dict(checks or {})
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def do_GET(self):
                server.handle(self)

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.httpd.daemon_threads = True
        self.port = self.httpd.server_address[1]
        self.host = host
        self._thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "HealthzServer":
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()

    def handle(self, h) -> None:
        path = h.path.split("?")[0].rstrip("/")
        try:
            if path in ("", "/healthz", "/healthz/ping"):
                for name, check in self.checks.items():
                    try:
                        if check() is False:
                            raise RuntimeError(f"check {name} failed")
                    except Exception as e:
                        return self._send(h, 500, f"unhealthy: {e}")
                return self._send(h, 200, "ok")
            if path == "/metrics":
                return self._send(h, 200, self.metrics.render(),
                                  "text/plain; version=0.0.4")
            self._send(h, 404, f"not found: {path}")
        except (BrokenPipeError, ConnectionResetError):
            pass

    @staticmethod
    def _send(h, code: int, text: str,
              ctype: str = "text/plain") -> None:
        payload = text.encode()
        h.send_response(code)
        h.send_header("Content-Type", ctype)
        h.send_header("Content-Length", str(len(payload)))
        h.end_headers()
        h.wfile.write(payload)
