"""Token-bucket rate limiter (ref: pkg/util/throttle.go over juju/ratelimit;
the scheduler's --bind-pods-qps/burst and client --kube-api-qps flags feed
this, plugin/cmd/kube-scheduler/app/server.go:145)."""

from __future__ import annotations

import threading
import time
from typing import Optional

from .clock import Clock, RealClock


class TokenBucketRateLimiter:
    def __init__(self, qps: float, burst: int, clock: Optional[Clock] = None):
        if qps <= 0:
            raise ValueError("qps must be positive")
        self.qps = float(qps)
        self.burst = max(1, int(burst))
        self.clock = clock or RealClock()
        self._tokens = float(self.burst)
        self._last = self.clock.now()
        self._lock = threading.Lock()

    def _refill(self) -> None:
        now = self.clock.now()
        self._tokens = min(self.burst,
                           self._tokens + (now - self._last) * self.qps)
        self._last = now

    def try_accept(self) -> bool:
        with self._lock:
            self._refill()
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False

    def accept(self) -> None:
        """Block until a token is available."""
        while True:
            with self._lock:
                self._refill()
                if self._tokens >= 1.0:
                    self._tokens -= 1.0
                    return
                wait = (1.0 - self._tokens) / self.qps
            self.clock.sleep(wait)

    def saturation(self) -> float:
        with self._lock:
            self._refill()
            return 1.0 - self._tokens / self.burst


class FakeAlwaysRateLimiter:
    def try_accept(self) -> bool:
        return True

    def accept(self) -> None:
        pass

    def saturation(self) -> float:
        return 0.0
