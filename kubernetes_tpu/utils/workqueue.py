"""Deduplicating work queue (ref: pkg/util/workqueue): an item added while
queued is coalesced; an item added while being processed is re-queued when
done — the invariant controllers rely on to never process one key
concurrently."""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Optional, Set


class WorkQueue:
    def __init__(self) -> None:
        self._cond = threading.Condition()
        self._queue: deque = deque()
        self._dirty: Set[Any] = set()
        self._processing: Set[Any] = set()
        self._shutdown = False

    def add(self, item: Any) -> None:
        with self._cond:
            if self._shutdown or item in self._dirty:
                return
            self._dirty.add(item)
            if item not in self._processing:
                self._queue.append(item)
                self._cond.notify()

    def get(self, timeout: Optional[float] = None) -> Optional[Any]:
        """Blocks for the next item; None on shutdown or timeout."""
        with self._cond:
            while not self._queue and not self._shutdown:
                if not self._cond.wait(timeout):
                    return None
            if not self._queue:
                return None
            item = self._queue.popleft()
            self._processing.add(item)
            self._dirty.discard(item)
            return item

    def done(self, item: Any) -> None:
        with self._cond:
            self._processing.discard(item)
            if item in self._dirty and not self._shutdown:
                self._queue.append(item)
                self._cond.notify()

    def shutdown(self) -> None:
        with self._cond:
            self._shutdown = True
            self._cond.notify_all()

    def __len__(self) -> int:
        with self._cond:
            return len(self._queue)
