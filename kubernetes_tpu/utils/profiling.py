"""Device profiling — the jax.profiler integration.

Reference: net/http/pprof mounted on the apiserver/scheduler/kubelet
(pkg/master/master.go:689-691, plugin/cmd/kube-scheduler/app/
server.go:131-135) + hack/grab-profiles.sh. The TPU-native analogue:
`device_trace` wraps a region in a jax.profiler trace (XPlane dumps
readable by TensorBoard / xprof), and `profiled_schedule` captures one
engine run — the equivalent of grabbing a scheduler CPU profile
mid-benchmark. Pairs with utils/trace.py (the over-threshold span
logger playing pkg/util/trace.go's role on the host side).
"""

from __future__ import annotations

import contextlib
import os
from typing import Optional


@contextlib.contextmanager
def device_trace(logdir: str):
    """Trace every XLA dispatch/execution in the region into `logdir`.

    Usage:
        with device_trace("/tmp/sched-trace"):
            engine.run_chunked(enc, 1024)
    """
    import jax

    os.makedirs(logdir, exist_ok=True)
    jax.profiler.start_trace(logdir)
    try:
        yield logdir
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def annotate(name: str):
    """Named sub-span inside a device trace (jax.profiler.TraceAnnotation
    — shows up as a labeled region in the timeline)."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield


def profiled_schedule(engine, enc, logdir: str,
                      chunk: Optional[int] = None):
    """One traced engine run -> (assigned, logdir). The grab-profiles.sh
    move: point it at a live encoder's output, read the dump in
    TensorBoard."""
    with device_trace(logdir):
        with annotate("batch-schedule"):
            if chunk:
                assigned, _ = engine.run_chunked(enc, chunk)
            else:
                assigned, _ = engine.run(enc)
    return assigned, logdir
