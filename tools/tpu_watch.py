#!/usr/bin/env python
"""Round-long TPU-tunnel watcher.

Probes the tunneled TPU on a schedule (the tunnel wedges for hours,
then recovers without notice) and, on the first healthy probe, runs
the full evidence capture (kubernetes_tpu/kubemark/tpu_evidence.py)
in a bounded subprocess. Re-captures hourly while the tunnel stays
healthy so BENCH_r{N} merges the freshest numbers.

Artifacts (all at the repo root):
- TPU_PROBES.jsonl  — one line per probe/capture attempt, timestamped.
  If the tunnel never opens all round, this file IS the evidence.
- TPU_EVIDENCE.json — freshest successful capture (atomic, partial
  sections survive a mid-capture wedge).
- .tpu_capture.lock — the shared advisory chip lock
  (kubernetes_tpu.kubemark.tpu_evidence chip-lock helpers): captures
  take it via atomic test-and-set and DEFER when bench.py's headline
  run holds it, so the two never contend for the one tunneled chip.

Start at round open:  nohup python tools/tpu_watch.py >/dev/null 2>&1 &
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

PROBE_LOG = os.path.join(REPO, "TPU_PROBES.jsonl")
EVIDENCE = os.path.join(REPO, "TPU_EVIDENCE.json")

PROBE_TIMEOUT = 120.0
PROBE_INTERVAL = 600.0       # wedged: probe every 10 min
CAPTURE_TIMEOUT = 2400.0
HEALTHY_INTERVAL = 1800.0    # healthy: refresh evidence every 30 min
                             # (each capture also folds into the
                             # per-section best artifact, so more
                             # samples only improve the ceiling)
FAILED_CAPTURE_INTERVAL = 900.0


def log(rec: dict) -> None:
    rec["ts"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    with open(PROBE_LOG, "a") as f:
        f.write(json.dumps(rec) + "\n")


def probe() -> bool:
    from kubernetes_tpu.utils.platform import probe_default_platform
    t0 = time.time()
    ok = probe_default_platform(timeout=PROBE_TIMEOUT)
    log({"event": "probe", "ok": ok,
         "elapsed_s": round(time.time() - t0, 1)})
    return ok


def capture() -> bool:
    from kubernetes_tpu.kubemark.tpu_evidence import (
        release_chip_lock, try_acquire_chip_lock)
    t0 = time.time()
    if not try_acquire_chip_lock(who="tpu_watch"):
        # bench.py's headline run (or a manual capture) holds the chip —
        # defer rather than contend for the one tunneled device
        log({"event": "capture-deferred", "reason": "foreign lock held"})
        return False
    try:
        res = subprocess.run(
            [sys.executable, "-m", "kubernetes_tpu.kubemark.tpu_evidence",
             "--out", EVIDENCE],
            capture_output=True, text=True, cwd=REPO,
            timeout=CAPTURE_TIMEOUT)
        ok = res.returncode == 0
        tail = (res.stdout + res.stderr)[-300:]
    except subprocess.TimeoutExpired:
        ok, tail = False, "capture timeout (tunnel wedged mid-run?)"
    finally:
        release_chip_lock()
    log({"event": "capture", "ok": ok,
         "elapsed_s": round(time.time() - t0, 1), "tail": tail})
    return ok


def main() -> None:
    log({"event": "start", "pid": os.getpid()})
    while True:
        # the probe log is the round's tunnel-health record: an
        # unexpected error (spawn failure, disk full) must be logged
        # and survived, not silently kill the watcher — a dead watcher
        # is indistinguishable from a wedged-all-round tunnel
        try:
            if probe():
                ok = capture()
                time.sleep(HEALTHY_INTERVAL if ok
                           else FAILED_CAPTURE_INTERVAL)
            else:
                time.sleep(PROBE_INTERVAL)
        except Exception as e:  # noqa: BLE001
            try:
                log({"event": "error", "error": repr(e)[:300]})
            except Exception:
                pass
            time.sleep(PROBE_INTERVAL)


if __name__ == "__main__":
    main()
