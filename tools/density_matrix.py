#!/usr/bin/env python
"""Density SLO matrix -> DENSITY.json.

The reference's density suite gates two pod-density tiers
(test/e2e/density.go:203-208: 3 and 30 pods/node) with hard latency
asserts (metrics_util.go:41-47 API p99 < 1s, :224-225 startup p50 < 5s).
This driver runs that matrix plus the north-star-scale product the r4
verdict called out as missing: 5000 nodes x 30 pods/node (150k pods) —
v1.0 density at north-star node count.

Gates are COUPLED to sample validity (kubemark/slo.py api_ok): a point
whose server-side sample window is starved reports api_slo_ok null,
never true.

Usage: python tools/density_matrix.py [--quick] [--cpu] [--out DENSITY.json]
  --quick runs only the 3 and 30 pods/node tiers at 1000 nodes
  (CI-sized run; skips the 50/100 tiers and the 150k-pod point).
  --cpu pins the CPU platform before jax init (the conftest move —
  JAX_PLATFORMS alone is overridden by the image's sitecustomize), so
  the standing artifact stays comparable round-over-round instead of
  silently moving to the tunneled chip when the flaky tunnel happens
  to be healthy (and contending with the watcher's captures).
"""

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def utc() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default=os.path.join(REPO, "DENSITY.json"))
    ap.add_argument("--quick", action="store_true",
                    help="run only the 1000-node 3 and 30 pods/node "
                         "tiers (skips 50/100 and the 150k-pod point)")
    ap.add_argument("--cpu", action="store_true",
                    help="pin the CPU platform before jax init for "
                         "round-over-round comparability")
    args = ap.parse_args()

    if args.cpu:
        from kubernetes_tpu.utils.platform import pin_cpu
        platform = pin_cpu()
    else:
        from kubernetes_tpu.utils.platform import ensure_live_platform
        platform, _probe = ensure_live_platform()

    from kubernetes_tpu.kubemark.slo import run_density_slo

    # (nodes, pods/node, timeout, max_pods, node_cpu): ALL FOUR
    # reference tiers at 1000 nodes (density.go:201-209 — 3, 30, then
    # the beyond-v1.0-goals 50 and 100 tiers, hollow nodes sized per
    # tier like the reference's clusters), then v1.0-density x
    # north-star scale
    matrix = [(1000, 3, 600.0, 40, "4"), (1000, 30, 900.0, 40, "4")]
    if not args.quick:
        matrix += [(1000, 50, 1200.0, 60, "8"),
                   (1000, 100, 1800.0, 110, "16"),
                   (5000, 30, 2400.0, 40, "4")]

    points = []
    for n_nodes, ppn, timeout, max_pods, node_cpu in matrix:
        t0 = time.time()
        r = run_density_slo(n_nodes=n_nodes, n_pods=n_nodes * ppn,
                            timeout_s=timeout,
                            max_pods_per_node=max_pods,
                            node_cpu=node_cpu)
        d = r.as_dict()
        d["wall_s"] = round(time.time() - t0, 1)
        points.append(d)
        print(json.dumps({"point": f"{n_nodes}x{ppn}",
                          "running": d["running"],
                          "elapsed_s": d["elapsed_s"],
                          "api_calls": d["api_calls"],
                          "api_slo_ok": d["api_slo_ok"],
                          "startup_slo_ok": d["startup_slo_ok"]}),
              flush=True)

    def gate(key):
        # null-coupled aggregation: any starved point poisons the
        # matrix verdict to null (the r4 verdict's decoupling bug)
        vals = [p[key] for p in points]
        if any(v is None for v in vals):
            return None
        return all(vals)

    doc = {
        "metric": "density_matrix",
        "ts": utc(),
        "ref": "test/e2e/density.go:203-208",
        "platform": platform,
        "points": points,
        "api_slo_ok": gate("api_slo_ok"),
        "startup_slo_ok": gate("startup_slo_ok"),
        "gate_coupling": "api_slo_ok is null unless every point met the "
                         "server-side sample floor (kubemark/slo.py)",
    }
    from kubernetes_tpu.kubemark.tpu_evidence import _atomic_write_json
    _atomic_write_json(args.out, doc)
    print(json.dumps({"out": args.out, "api_slo_ok": doc["api_slo_ok"],
                      "startup_slo_ok": doc["startup_slo_ok"]}))


if __name__ == "__main__":
    main()
