#!/usr/bin/env python
"""Obs report: burn-rate table + sparklines from a metrics-plane
series, and the bench-trajectory regression gate (--against).

The input is either artifact the plane produces, auto-detected:

  - a FleetScraper series export (FleetScraper.export_json(), also
    what a flight-recorder bundle's series.json holds a tail of)
  - a bench.py artifact whose `metricsplane` section carries the same
    export under "series" plus the recorded alert timeline
    (python bench.py --timeseries > BENCH_rNN.json)

The burn-rate table REPLAYS the evaluator over the series (the
pinned kubemark/slo.py FLEET_SLOS) — on a bench artifact the replay
is cross-checked against the alert timeline the run recorded, so a
drifted evaluator shows up as a mismatch, not a silent pass.

--against compares this artifact's headline scalars to a previous
round's BENCH_r*.json (throughput up is good, p99/overhead up is
bad) and exits 1 on any move beyond the noise band — the trajectory
regression gate.

Usage:
  python tools/obs_report.py series.json
  python tools/obs_report.py BENCH_r06.json --against BENCH_r05.json
  python tools/obs_report.py BENCH_r06.json --band 0.15

stdlib-only by design: it must run anywhere the repo does, including
the bare soak containers.
"""

import argparse
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from kubernetes_tpu.kubemark.slo import FLEET_SLOS
from kubernetes_tpu.obs.metricsplane import BurnRateEvaluator

#: 8-level block ramp; every sparkline row is normalized to its own max
BLOCKS = "▁▂▃▄▅▆▇█"


def load_doc(source: str) -> dict:
    if source == "-":
        return json.load(sys.stdin)
    with open(source) as fh:
        return json.load(fh)


def split_doc(doc: dict):
    """-> (series_export, bench_headline, recorded_alerts). Accepts a
    bare scraper export, a bench headline dict, or the round-capture
    wrapper the BENCH_r*.json files use ({"parsed": headline, ...})."""
    if isinstance(doc.get("parsed"), dict):
        doc = doc["parsed"]
    if "samples" in doc:               # bare FleetScraper export
        return doc, None, []
    mp = doc.get("metricsplane")
    if isinstance(mp, dict):
        return mp.get("series") or {"samples": []}, doc, \
            list(mp.get("alerts") or [])
    if "series" in doc:                # a bare metricsplane section
        return doc.get("series") or {"samples": []}, None, \
            list(doc.get("alerts") or [])
    return {"samples": []}, doc, []


# ------------------------------------------------------------ series


def counter_track(samples, name):
    """Cumulative fleet total per sample (summed across label sets)."""
    return [sum(s.get("counters", {}).get(name, {}).values())
            for s in samples]


def hist_count_track(samples, name):
    return [sum(d.get("count", 0)
                for d in s.get("histograms", {}).get(name, {}).values())
            for s in samples]


def deltas(track):
    return [max(0.0, b - a) for a, b in zip(track, track[1:])]


def sparkline(vals) -> str:
    top = max(vals) if vals else 0.0
    if top <= 0:
        return BLOCKS[0] * len(vals)
    return "".join(BLOCKS[min(7, int(v / top * 7.999))] for v in vals)


def series_report(export: dict, top: int) -> str:
    samples = export.get("samples", [])
    lines = [f"{len(samples)} samples, targets="
             f"{','.join(export.get('targets', [])) or '?'}, "
             f"cadence={export.get('cadence_s', '?')}s, "
             f"resets={export.get('resets_total', 0)}, "
             f"scrape_errors={export.get('errors_total', 0)}"]
    if not samples:
        return "\n".join(lines)
    # per-sample rate sparklines, busiest families first (counters and
    # histogram observation counts share one ranking)
    names = {}
    for s in samples:
        for n in s.get("counters", {}):
            names.setdefault(n, "counter")
        for n in s.get("histograms", {}):
            names.setdefault(n, "histogram")
    rows = []
    for n, kind in names.items():
        track = (counter_track(samples, n) if kind == "counter"
                 else hist_count_track(samples, n))
        d = deltas(track)
        rows.append((sum(d), n, kind, d, track[-1] if track else 0.0))
    rows.sort(key=lambda r: (-r[0], r[1]))
    shown = rows[:top]
    width = max((len(r[1]) for r in shown), default=10)
    lines.append("")
    lines.append(f"{'family':<{width}}  {'total':>12}  per-sample rate")
    for _, n, kind, d, final in shown:
        lines.append(f"{n:<{width}}  {final:>12.1f}  {sparkline(d)}")
    if len(rows) > top:
        lines.append(f"... {len(rows) - top} quieter families "
                     f"elided (--top to widen)")
    return "\n".join(lines)


# --------------------------------------------------------- burn rates


def burn_report(export: dict, recorded_alerts) -> str:
    samples = export.get("samples", [])
    ev = BurnRateEvaluator(list(FLEET_SLOS))
    for s in samples:
        ev.observe(s)
    lines = [f"{'slo':<26} {'objective':>9} {'fast':>9} {'slow':>9} "
             f"{'trips':>5} {'clears':>6} {'active':>6}"]
    for slo in FLEET_SLOS:
        mine = [e for e in ev.events if e.slo == slo.name]
        fast = ev._burn(slo, slo.fast_window) if samples else 0.0
        slow = ev._burn(slo, slo.slow_window) if samples else 0.0
        lines.append(
            f"{slo.name:<26} {slo.objective:>9} {fast:>9.2f} "
            f"{slow:>9.2f} "
            f"{sum(e.action == 'TRIP' for e in mine):>5} "
            f"{sum(e.action == 'CLEAR' for e in mine):>6} "
            f"{str(ev.active(slo.name)).lower():>6}")
    if ev.events:
        lines.append("")
        lines.append("alert timeline (replayed from the series):")
        for e in ev.events:
            lines.append(f"  sample {e.sample:>3} t={e.t:<8g} "
                         f"{e.action:<5} {e.slo} "
                         f"(fast={e.fast_burn:.1f} slow={e.slow_burn:.1f})")
    if recorded_alerts:
        replayed = [[e.sample, e.slo, e.action] for e in ev.events]
        recorded = [[a["sample"], a["slo"], a["action"]]
                    for a in recorded_alerts]
        lines.append("")
        if replayed == recorded:
            lines.append(f"recorded alert timeline matches the replay "
                         f"({len(recorded)} edges) -- evaluator is "
                         f"deterministic over this series")
        else:
            lines.append(f"MISMATCH: run recorded {recorded} but the "
                         f"replay produced {replayed} -- the evaluator "
                         f"or the series drifted")
    return "\n".join(lines)


# -------------------------------------------- the trajectory regression


def _scalars(doc: dict) -> dict:
    """Comparable headline scalars from a bench dict, any round's
    shape (the slo section was flat before it grew density_points)."""
    out = {}
    if isinstance(doc.get("value"), (int, float)):
        out["e2e_pods_per_sec"] = float(doc["value"])
    if isinstance(doc.get("engine_only_pods_per_sec"), (int, float)):
        out["engine_pods_per_sec"] = float(doc["engine_only_pods_per_sec"])
    slo = doc.get("slo")
    if isinstance(slo, dict):
        points = slo.get("density_points")
        if isinstance(points, list):
            for i, p in enumerate(points):
                if isinstance(p.get("api_p99_ms"), (int, float)):
                    out[f"slo[{i}].api_p99_ms"] = float(p["api_p99_ms"])
        elif isinstance(slo.get("api_p99_ms"), (int, float)):
            out["slo.api_p99_ms"] = float(slo["api_p99_ms"])
    wl = doc.get("workload")
    if isinstance(wl, dict) and isinstance(wl.get("bind_p99_s"),
                                           (int, float)):
        out["workload.bind_p99_s"] = float(wl["bind_p99_s"])
    mp = doc.get("metricsplane")
    if isinstance(mp, dict) and isinstance(mp.get("overhead_frac"),
                                           (int, float)):
        out["scrape.overhead_frac"] = float(mp["overhead_frac"])
    pr = doc.get("preemption")
    if isinstance(pr, dict) and isinstance(pr.get("surge_bind_p99_s"),
                                           (int, float)):
        # no _per_sec suffix -> lower-is-better in the trajectory gate
        out["preemption.surge_bind_p99_s"] = float(pr["surge_bind_p99_s"])
    sv = doc.get("serving")
    if isinstance(sv, dict):
        arm = sv.get("arm")
        if isinstance(arm, dict):
            if isinstance(arm.get("deliver_events_per_sec"),
                          (int, float)):
                out["serving.deliver_events_per_sec"] = float(
                    arm["deliver_events_per_sec"])
            if isinstance(arm.get("lag_p99_ms"), (int, float)):
                out["serving.lag_p99_ms"] = float(arm["lag_p99_ms"])
    return out


def _recover_scalars(wrapper: dict) -> dict:
    """Best-effort baseline recovery when a round's wrapper has
    parsed:null (the driver's tail got truncated mid-JSON): fish the
    headline throughput out of the raw tail text."""
    tail = wrapper.get("tail") or ""
    m = re.search(r'"value":\s*([0-9.]+)', tail)
    if m:
        return {"e2e_pods_per_sec": float(m.group(1))}
    m = re.search(r'per_sec":\s*\[([^\]]+)\]', tail)
    if m:
        try:
            runs = [float(x) for x in m.group(1).split(",")]
            return {"e2e_pods_per_sec": max(runs)}
        except ValueError:
            pass
    return {}


#: direction per scalar: +1 means up is good (throughput), -1 means
#: up is bad (latency, overhead)
def _direction(name: str) -> int:
    return 1 if name.endswith("_per_sec") else -1


def against_report(current: dict, baseline_path: str,
                   band: float):
    base_doc = load_doc(baseline_path)
    inner = base_doc.get("parsed") if isinstance(base_doc.get("parsed"),
                                                 dict) else base_doc
    base = _scalars(inner) if isinstance(inner, dict) else {}
    if not base and "tail" in base_doc:
        base = _recover_scalars(base_doc)
    cur = _scalars(current)
    shared = sorted(set(base) & set(cur))
    lines = [f"trajectory vs {os.path.basename(baseline_path)} "
             f"(noise band ±{band:.0%}):"]
    if not shared:
        lines.append("  no comparable scalars in both artifacts -- "
                     "nothing to gate")
        return "\n".join(lines), False
    width = max(len(n) for n in shared)
    regressed = False
    for n in shared:
        b, c = base[n], cur[n]
        rel = (c - b) / b if b else 0.0
        bad = _direction(n) * rel < -band
        regressed |= bad
        verdict = "REGRESSION" if bad else (
            "improved" if _direction(n) * rel > band else "flat")
        lines.append(f"  {n:<{width}}  {b:>12.2f} -> {c:>12.2f} "
                     f"({rel:+7.1%})  {verdict}")
    return "\n".join(lines), regressed


def main() -> None:
    ap = argparse.ArgumentParser(
        description="burn-rate table + sparklines from a metrics-plane "
                    "series; --against gates the bench trajectory")
    ap.add_argument("source", help="FleetScraper export or bench.py "
                                   "artifact (BENCH_r*.json), '-' for "
                                   "stdin")
    ap.add_argument("--against", metavar="BENCH_rNN.json",
                    help="previous round's artifact: compare headline "
                         "scalars, exit 1 on a move beyond the band")
    ap.add_argument("--band", type=float, default=0.25,
                    help="relative noise band for --against (default "
                         "0.25: the box shows ±20%% run-to-run)")
    ap.add_argument("--top", type=int, default=12,
                    help="sparkline rows to show (busiest families "
                         "first, default 12)")
    args = ap.parse_args()

    doc = load_doc(args.source)
    export, bench, recorded_alerts = split_doc(doc)

    print(series_report(export, args.top))
    print()
    print(burn_report(export, recorded_alerts))

    if args.against:
        if bench is None:
            bench = {}
        print()
        text, regressed = against_report(bench, args.against, args.band)
        print(text)
        if regressed:
            sys.exit(1)


if __name__ == "__main__":
    main()
