#!/usr/bin/env python
"""Trace report: stage-latency decomposition + critical path from a
span dump, with optional Chrome/Perfetto trace-event export.

The input is either format the debug endpoint serves — the span dump
of GET /debug/trace?format=spans (a JSON list of Span.to_dict dicts,
also what a harness writes from obs.tracer().spans()) or the bare
GET /debug/trace trace-event JSON, auto-detected — as a file path,
`-` for stdin, or an http(s) URL to a live apiserver.

Usage:
  python tools/trace_report.py spans.json
  python tools/trace_report.py http://127.0.0.1:8080/debug/trace?format=spans
  python tools/trace_report.py spans.json --trace TRACE_ID   # one trace
  python tools/trace_report.py spans.json --perfetto out.json
    (open out.json in ui.perfetto.dev or chrome://tracing)

stdlib-only by design: it must run anywhere the repo does, including
the bare soak containers.
"""

import argparse
import json
import os
import sys
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from kubernetes_tpu.obs.export import (critical_path, stage_totals,
                                       to_trace_events)
from kubernetes_tpu.utils.metrics import OBS_STAGES


def _events_to_spans(events: list) -> list:
    """Fold trace-event JSON (what bare GET /debug/trace serves) back
    into span dicts — the "X" events carry the full span identity in
    args, so both endpoint formats feed the same reports."""
    spans = []
    for e in events:
        if e.get("ph") != "X":
            continue
        a = dict(e.get("args") or {})
        start = e["ts"] / 1e6
        steps = [[t / 1e6, m] for t, m in a.pop("steps", [])]
        spans.append({
            "name": e["name"],
            "trace_id": a.pop("trace_id", ""),
            "span_id": a.pop("span_id", ""),
            "parent_id": a.pop("parent_id", None),
            "status": a.pop("status", "ok"),
            "stage": None if e.get("cat") in (None, "span") else e["cat"],
            "start": start,
            "end": start + e["dur"] / 1e6,
            "attrs": a,
            "steps": steps})
    return spans


def load_spans(source: str) -> list:
    if source == "-":
        data = json.load(sys.stdin)
    elif source.startswith(("http://", "https://")):
        with urllib.request.urlopen(source, timeout=10) as resp:
            data = json.loads(resp.read().decode())
    else:
        with open(source) as fh:
            data = json.load(fh)
    if data and isinstance(data[0], dict) and "ph" in data[0]:
        return _events_to_spans(data)
    return data


def _quantile(samples, q):
    if not samples:
        return 0.0
    s = sorted(samples)
    return s[min(len(s) - 1, int(q * len(s)))]


def stage_table(spans: list) -> str:
    """Per-stage count/total/p50/p99 over finished staged spans, in
    pipeline order (the OBS_STAGES glossary), then any stray stages."""
    by_stage = {}
    for s in spans:
        if s.get("stage") is None or s.get("end") is None:
            continue
        by_stage.setdefault(s["stage"], []).append(s["end"] - s["start"])
    order = [st for st in OBS_STAGES if st in by_stage]
    order += sorted(set(by_stage) - set(OBS_STAGES))
    lines = [f"{'stage':<10} {'count':>7} {'total_s':>10} "
             f"{'p50_ms':>9} {'p99_ms':>9}"]
    for st in order:
        d = by_stage[st]
        lines.append(f"{st:<10} {len(d):>7} {sum(d):>10.3f} "
                     f"{_quantile(d, 0.5) * 1e3:>9.2f} "
                     f"{_quantile(d, 0.99) * 1e3:>9.2f}")
    return "\n".join(lines)


def path_report(spans: list, trace_id: str) -> str:
    path = critical_path(spans, trace_id)
    if not path:
        return f"trace {trace_id}: no finished spans"
    t0 = path[0]["start"]
    lines = [f"critical path of trace {trace_id} "
             f"({(path[-1]['end'] - t0) * 1e3:.2f}ms root to last):"]
    for s in path:
        lines.append(
            f"  +{(s['start'] - t0) * 1e3:9.2f}ms "
            f"{(s['end'] - s['start']) * 1e3:9.2f}ms "
            f"[{s.get('stage') or '-':<8}] {s['name']} ({s['status']})")
    return "\n".join(lines)


def main() -> None:
    ap = argparse.ArgumentParser(
        description="stage decomposition + critical path from a span dump")
    ap.add_argument("source", help="span-dump file, '-' for stdin, or the "
                                   "/debug/trace?format=spans URL")
    ap.add_argument("--trace", metavar="TRACE_ID",
                    help="report one trace's critical path (default: the "
                         "trace whose root span ran longest)")
    ap.add_argument("--perfetto", metavar="OUT",
                    help="also write Chrome/Perfetto trace-event JSON")
    args = ap.parse_args()

    spans = load_spans(args.source)
    done = [s for s in spans if s.get("end") is not None]
    print(f"{len(spans)} spans ({len(done)} finished), "
          f"{len({s['trace_id'] for s in spans})} traces")
    print()
    print(stage_table(spans))

    trace_id = args.trace
    if trace_id is None and done:
        # default: the slowest root span's trace — the whale a latency
        # investigation opens with
        roots = [s for s in done if not s["parent_id"]] or done
        trace_id = max(roots,
                       key=lambda s: s["end"] - s["start"])["trace_id"]
    if trace_id:
        print()
        print(path_report(spans, trace_id))

    if args.perfetto:
        events = to_trace_events(spans)
        with open(args.perfetto, "w") as fh:
            json.dump(events, fh, sort_keys=True, separators=(",", ":"))
        print(f"\nwrote {len(events)} trace events to {args.perfetto} "
              f"(open in ui.perfetto.dev)")


if __name__ == "__main__":
    main()
