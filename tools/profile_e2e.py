#!/usr/bin/env python
"""Sampling profile of the end-to-end scheduling benchmark.

The box has ONE core, so e2e wall time ~= total Python work + GIL
waits; a cross-thread sampler (sys._current_frames) is the right
instrument — cProfile sees only one thread and py-spy is not in the
image. Samples are timestamped and scoped to the MEASURED window
(BenchmarkResult.started_at .. +elapsed_s) so fleet setup and warmup
compiles don't pollute the breakdown. Leaves are recorded at line
granularity; inclusive counts at function granularity.

Output: PROFILE_e2e.md — per-thread window share, top leaf lines
(runnable vs waiting), top inclusive frames.

Usage: JAX_PLATFORMS=cpu python tools/profile_e2e.py [--nodes N]
       [--pods P] [--backend native]
"""

import argparse
import collections
import json
import os
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# leaf functions that mean "parked", not "burning the core"
WAIT_LEAVES = {"wait", "acquire", "_wait_for_tstate_lock", "select",
               "poll", "recv", "accept", "read", "sleep", "epoll",
               "_recv_into", "readinto"}

# Store commit phase split (the two-phase commit decomposition): a
# sample inside the publish frames is watch fan-out running OFF the
# ledger lock; a sample inside a commit verb WITHOUT a publish frame is
# the in-lock ledger window (stage+ledger). The per-role ratio is the
# direct readout of how much of each committer's store time still
# holds the lock.
STORE_PUBLISH_FRAMES = {"store.py:_drain_publish", "store.py:_fanout",
                        "store.py:_filtered_event"}
STORE_COMMIT_FRAMES = {"store.py:create", "store.py:create_batch",
                       "store.py:set", "store.py:update",
                       "store.py:guaranteed_update", "store.py:delete",
                       "store.py:batch", "store.py:commit_txn",
                       # native commit path: these frames are the
                       # Python-side STAGING half (decode/apply/encode
                       # + the kv_commit_txn call, which releases the
                       # GIL for the mutex window). The publish half
                       # runs on the engine's own publisher thread —
                       # invisible to sys._current_frames by design;
                       # its cost comes from kv_stats (section below).
                       "native_store.py:_txn_commit_native",
                       "native_store.py:_kv_commit",
                       "native_store.py:_create_batch_walled"}

# Device-execution frames: a tick with one thread inside these AND
# another thread inside a ledger commit is the async bind pipeline
# doing both halves of its job at once (tile N+1 encoding/scanning
# while tile N's bindings commit) — the scan/commit overlap readout.
DEVICE_FRAMES = {"engine.py:run_chunked", "incremental.py:encode_tile"}


def thread_group(name: str) -> str:
    """Collapse per-instance thread names into roles so 30 writers (or
    several reflectors of one kind) aggregate."""
    if "(writer)" in name:
        return "writers(30)"
    return name


class Sampler(threading.Thread):
    def __init__(self, interval: float):
        super().__init__(daemon=True, name="profiler-sampler")
        self.interval = interval
        self.stop_ev = threading.Event()
        # [(ts, [(thread_name, leaf_site, stack_funcs)])]
        self.ticks = []

    def run(self):
        me = threading.get_ident()
        names = {}
        while not self.stop_ev.is_set():
            for t in threading.enumerate():
                names[t.ident] = t.name
            frames = sys._current_frames()
            ts = time.time()
            snap = []
            for tid, frame in frames.items():
                if tid == me:
                    continue
                name = names.get(tid, str(tid))
                f = frame
                leaf = None
                stack = []
                while f is not None:
                    code = f.f_code
                    fn = (f"{os.path.basename(code.co_filename)}:"
                          f"{code.co_name}")
                    if leaf is None:
                        leaf = f"{fn}:{f.f_lineno}"
                    stack.append(fn)
                    f = f.f_back
                snap.append((name, leaf, stack))
            self.ticks.append((ts, snap))
            time.sleep(self.interval)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=5000)
    ap.add_argument("--pods", type=int, default=30000)
    ap.add_argument("--interval", type=float, default=0.002)
    ap.add_argument("--backend", default=None,
                    help="pass 'native' to run the native kv store")
    ap.add_argument("--out", default=os.path.join(REPO, "PROFILE_e2e.md"))
    ap.add_argument("--full-uploads", action="store_true",
                    help="disable delta scatters: re-upload the full node "
                         "tables every tile (control arm of the A/B)")
    args = ap.parse_args()

    if os.environ.get("JAX_PLATFORMS"):
        # the image's sitecustomize pins the axon platform past the
        # env var; the config update must follow the jax import
        import jax
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    from kubernetes_tpu.kubemark.benchmark import run_scheduling_benchmark
    registry = None
    native_store = None
    if args.backend == "native":
        from kubernetes_tpu.api.registry import Registry
        from kubernetes_tpu.core.native_store import NativeStore
        native_store = NativeStore()
        registry = Registry(store=native_store)

    s = Sampler(args.interval)
    s.start()
    r = run_scheduling_benchmark(args.nodes, args.pods, "batch",
                                 registry=registry,
                                 delta_uploads=not args.full_uploads)
    s.stop_ev.set()
    s.join(timeout=2)
    # engine-side split for the native commit path: the publisher runs
    # off the GIL, so the sampler cannot see it — the engine's own
    # nanosecond counters (kv_stats) are the only instrument
    nstats = (native_store.publish_stats() if native_store is not None
              else {})

    t0, t1 = r.started_at, r.started_at + r.elapsed_s
    window = [(ts, snap) for ts, snap in s.ticks if t0 <= ts <= t1]
    n_ticks = len(window)
    leaf = collections.Counter()       # (group, leaf_line) -> count
    incl = collections.Counter()       # (group, func) -> count
    by_thread = collections.Counter()  # group -> count
    run_by_thread = collections.Counter()
    phase = collections.Counter()      # (group, "ledger"|"publish") -> count
    overlap_ticks = 0                  # device scan ∥ ledger commit
    device_ticks = 0
    ledger_ticks = 0
    hold_runs = collections.defaultdict(list)  # role -> [run lengths]
    hold_cur = collections.Counter()           # role -> current run
    for _ts, snap in window:
        tick_device = False
        tick_ledger_roles = set()
        for name, lf, stack in snap:
            g = thread_group(name)
            by_thread[g] += 1
            leaf[(g, lf)] += 1
            if lf.rsplit(":", 2)[-2] not in WAIT_LEAVES:
                run_by_thread[g] += 1
            frames = set(stack)
            if frames & DEVICE_FRAMES:
                tick_device = True
            if frames & STORE_PUBLISH_FRAMES:
                phase[(g, "publish")] += 1
            elif frames & STORE_COMMIT_FRAMES:
                phase[(g, "ledger")] += 1
                tick_ledger_roles.add(g)
            for fn in frames:
                incl[(g, fn)] += 1
        if tick_device:
            device_ticks += 1
        if tick_ledger_roles:
            ledger_ticks += 1
        if tick_device and tick_ledger_roles:
            overlap_ticks += 1
        # ledger-hold run lengths: consecutive ticks a role stays inside
        # the in-lock phase ~ one lock-hold window (0.002s resolution)
        for g in list(hold_cur):
            if g not in tick_ledger_roles:
                hold_runs[g].append(hold_cur.pop(g))
        for g in tick_ledger_roles:
            hold_cur[g] += 1
    for g, c in hold_cur.items():
        hold_runs[g].append(c)

    total = sum(by_thread.values())
    wait = sum(c for (g, site), c in leaf.items()
               if site.rsplit(":", 2)[-2] in WAIT_LEAVES)

    def leaf_rows(n=40):
        rows = []
        for (g, site), c in leaf.most_common(n):
            kind = ("wait" if site.rsplit(":", 2)[-2] in WAIT_LEAVES
                    else "RUN")
            rows.append(f"| {g} | {site} | {c} | "
                        f"{100 * c / max(1, n_ticks):.1f}% | {kind} |")
        return "\n".join(rows)

    def incl_rows(n=30):
        rows = []
        for (g, fn), c in incl.most_common(n):
            rows.append(f"| {g} | {fn} | {c} | "
                        f"{100 * c / max(1, n_ticks):.1f}% |")
        return "\n".join(rows)

    with open(args.out, "w") as f:
        f.write(f"""# e2e profile — {args.nodes} nodes / {args.pods} pods

Generated by tools/profile_e2e.py (cross-thread sampler; samples
scoped to the MEASURED window only — setup/warmup excluded). One-core
box: a RUNNING leaf either holds the GIL or is runnable awaiting it;
the sum of RUN leaves ~ the window's total Python work.
Backend: {args.backend or 'python-registry'}.

Result: **{r.pods_per_sec:.0f} pods/s** ({r.scheduled}/{r.n_pods} in
{r.elapsed_s:.2f}s). Window ticks: {n_ticks}
(~{1000 * r.elapsed_s / max(1, n_ticks):.1f}ms effective tick),
{total} thread-samples, {100 * wait / max(1, total):.0f}% in wait
leaves.

## Per-role totals (RUN samples = GIL demand)

| role | samples | RUN samples | RUN % of window |
|---|---|---|---|
""")
        for g, c in by_thread.most_common(18):
            f.write(f"| {g} | {c} | {run_by_thread[g]} | "
                    f"{100 * run_by_thread[g] / max(1, n_ticks):.1f}% |\n")
        f.write("""
## Store commit: in-lock (ledger) vs publish

Samples inside a store commit verb split by phase — `ledger` frames
hold the store's ledger lock (stage + mutation), `publish` frames are
the watch fan-out the two-phase commit moved OFF that lock. The
in-lock share is what the three committers still serialize on.

| role | ledger (in-lock) | publish (off-lock) | in-lock share |
|---|---|---|---|
""")
        roles = sorted({g for g, _p in phase})
        for g in roles:
            led, pub = phase[(g, "ledger")], phase[(g, "publish")]
            tot = led + pub
            f.write(f"| {g} | {led} | {pub} | "
                    f"{100 * led / max(1, tot):.0f}% |\n")
        if nstats:
            commits = max(1, nstats.get("commits", 0))
            batches = max(1, nstats.get("published_batches", 0))
            led_ms = nstats.get("ledger_ns", 0) / 1e6
            pub_ms = nstats.get("publish_ns", 0) / 1e6
            f.write(f"""
## Native commit path: engine-side ledger vs publish (kv_stats)

The sampler above only sees the Python STAGING half of a native
commit — the engine mutex window and the publisher thread run with
the GIL released, so their cost comes from the engine's own
monotonic-clock counters. `ledger` is kv_commit_txn's in-mutex window
(validate + apply + WAL frame); `publish` is the ring drain on the
native publisher thread — the half that used to hold the Python
ledger lock and now runs concurrently with the next tile's staging.

| counter | value |
|---|---|
| commits (kv_commit_txn calls) | {nstats.get("commits", 0)} |
| ledger time, total | {led_ms:.1f}ms |
| ledger time per commit | {1000 * led_ms / commits:.1f}us |
| published batches | {nstats.get("published_batches", 0)} |
| publish time, total | {pub_ms:.1f}ms |
| publish time per batch | {1000 * pub_ms / batches:.1f}us |
| publish / (ledger+publish) | {100 * pub_ms / max(1e-9, led_ms + pub_ms):.0f}% |
| WAL frames / bytes | {nstats.get("wal_frames", 0)} / {nstats.get("wal_bytes", 0):,} |
| revision / published_rev | {nstats.get("revision", 0)} / {nstats.get("published_rev", 0)} |
""")
        tick_s = r.elapsed_s / max(1, n_ticks)

        def pctile(xs, p):
            if not xs:
                return 0.0
            xs = sorted(xs)
            return xs[min(len(xs) - 1, int(p * len(xs)))] * tick_s

        f.write(f"""
## Pipeline overlap (scan ∥ commit)

A window tick is *overlapped* when one thread is inside a device
frame ({", ".join(sorted(DEVICE_FRAMES))}) while another holds the
ledger — the async bind pipeline executing tile N+1 on device while
tile N's bindings commit. Ledger-hold percentiles are run lengths of
consecutive in-lock ticks per committer (~one lock-hold window,
{1000 * tick_s:.1f}ms resolution).

- device-execution ticks: {device_ticks} ({100 * device_ticks / max(1, n_ticks):.1f}% of window)
- ledger-commit ticks: {ledger_ticks} ({100 * ledger_ticks / max(1, n_ticks):.1f}% of window)
- **overlapped ticks: {overlap_ticks}** ({100 * overlap_ticks / max(1, n_ticks):.1f}% of window, {100 * overlap_ticks / max(1, device_ticks):.1f}% of device time)

| committer | holds | p50 hold | p99 hold | max hold |
|---|---|---|---|---|
""")
        for g in sorted(hold_runs):
            runs_g = hold_runs[g]
            f.write(f"| {g} | {len(runs_g)} | "
                    f"{1000 * pctile(runs_g, 0.50):.1f}ms | "
                    f"{1000 * pctile(runs_g, 0.99):.1f}ms | "
                    f"{1000 * max(runs_g) * tick_s:.1f}ms |\n")
        us = r.upload_stats or {}
        n_full = us.get("full_tiles", 0)
        n_delta = us.get("delta_tiles", 0)
        n_reuse = us.get("reuse_tiles", 0)
        n_tiles = max(1, n_full + n_delta + n_reuse)
        # price of one full upload: measured if the window moved any,
        # else the engine's table-size gauge (a steady delta-arm window
        # moves none — that's the point)
        per_full = (us.get("full_bytes", 0) / n_full if n_full
                    else us.get("table_bytes", 0))
        per_delta = (us.get("delta_bytes", 0) / n_delta
                     if n_delta else 0.0)
        per_pod = us.get("pod_bytes", 0) / n_tiles
        arm = "full-upload (control)" if args.full_uploads else "delta-scatter"
        f.write(f"""
## Host->device transfer per tile ({arm} arm)

Node-table bytes moved host->device per scheduling tile, from the
engine's upload counters (measured window + warmup resets excluded).
A *full* tile re-uploads both sharded tables; a *delta* tile scatters
only rows whose dirty generation advanced; a *reuse* tile touches the
device mirror not at all (chained tiles carrying State on device).
The pod stream (P-sized pending-pod arrays) is uploaded every tile in
both arms and is listed separately. Run with `--full-uploads` for the
control arm.

| metric | value |
|---|---|
| full-upload tiles | {n_full} |
| delta-scatter tiles | {n_delta} |
| mirror-reuse tiles | {n_reuse} |
| bytes per full upload | {per_full:,.0f} |
| bytes per delta tile | {per_delta:,.0f} |
| pod-stream bytes per tile | {per_pod:,.0f} |
| node-table bytes, total | {us.get("full_bytes", 0) + us.get("delta_bytes", 0):,} |
| vs all-full tiles (est.) | {n_tiles * per_full:,.0f} |
| node-table reduction | {(f"{n_tiles * per_full / (us['full_bytes'] + us['delta_bytes']):.1f}x" if us.get("full_bytes", 0) + us.get("delta_bytes", 0) else "every tile reused the mirror (0 bytes)")} |
""")
        f.write(f"""
## Top leaf lines

| role | site (file:func:line) | samples | % of ticks | kind |
|---|---|---|---|---|
{leaf_rows()}

## Top inclusive functions

| role | function | samples | % of ticks |
|---|---|---|---|
{incl_rows()}
""")
    print(json.dumps({"pods_per_sec": round(r.pods_per_sec, 1),
                      "elapsed_s": round(r.elapsed_s, 2),
                      "scheduled": r.scheduled,
                      "window_ticks": n_ticks,
                      "overlap_ticks": overlap_ticks,
                      "device_ticks": device_ticks,
                      "ledger_ticks": ledger_ticks,
                      "upload_stats": r.upload_stats,
                      "native_publish_stats": nstats or None,
                      "out": args.out}))


if __name__ == "__main__":
    main()
