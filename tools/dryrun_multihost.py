#!/usr/bin/env python
"""Multi-HOST dryrun: the sharded scheduling scan across OS processes.

dryrun_multichip proves the single-process multi-device mesh; this
proves the DCN shape — two (or more) separate processes, each owning a
slice of the global device set, joined by jax.distributed into ONE
mesh. The batch engine's node-axis sharding then makes its per-step
argmax reduce ACROSS processes (gloo collectives on CPU, the exact
lowering slot ICI/DCN collectives fill on real multi-host TPU — the
jax.distributed + Mesh code path is identical, only the transport
differs). Bindings are asserted bit-equal to a single-process run of
the same encode.

Launcher:  python tools/dryrun_multihost.py [--procs 4]
               [--devices-per-proc 2] [--out MULTIHOST.json]
Worker:    python tools/dryrun_multihost.py --worker <id> --procs N \
               --port P   (spawned by the launcher)

The launcher writes MULTIHOST.json so the DCN-path proof is a standing
per-round artifact (bench.py regenerates it every round), not a
one-time capture.
"""

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DEVICES_PER_PROC = 2


def worker(proc_id: int, nprocs: int, port: int,
           devices_per_proc: int = DEVICES_PER_PROC) -> None:
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = \
        f"--xla_force_host_platform_device_count={devices_per_proc}"
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=nprocs, process_id=proc_id)

    import numpy as np
    from jax.sharding import Mesh

    from __graft_entry__ import _tiny_snapshot_inline
    from kubernetes_tpu.sched.device import BatchEngine, encode_snapshot

    n_global = jax.device_count()
    assert n_global == nprocs * devices_per_proc, n_global
    mesh = Mesh(np.array(jax.devices()), ("nodes",))
    engine = BatchEngine(mesh=mesh)
    assert engine.spans_processes

    # identical encode on every host (deterministic snapshot) — the
    # replicated-host-state model of a real multi-host scheduler
    snap = _tiny_snapshot_inline(n_nodes=2 * n_global, n_pending=12)
    enc = encode_snapshot(snap, node_pad_to=n_global)
    assigned, _state = engine.run(enc)
    assigned = np.asarray(assigned[:enc.n_pods])

    # single-process reference: same encode, no mesh, local device
    single = BatchEngine()
    expect, _ = single.run(enc)
    expect = np.asarray(expect[:enc.n_pods])
    assert np.array_equal(assigned, expect), (assigned, expect)
    assert int((assigned >= 0).sum()) > 0, "nothing scheduled"

    # the PRODUCTION pipeline path across processes: run_chunked
    # executes the pod axis as fixed-size chunks with the carry
    # threaded between dispatches as an ON-DEVICE GLOBAL array — the
    # cross-host state never round-trips through a host. Must be
    # bit-equal to the one-shot scan and to the single-process
    # chunked run.
    half = max(1, enc.n_pods // 2)
    chained, _carry = engine.run_chunked(enc, half)
    exp_chunked, _ = single.run_chunked(enc, half)
    chained = np.asarray(chained)[:enc.n_pods]
    assert np.array_equal(chained, np.asarray(exp_chunked)[:enc.n_pods])
    assert np.array_equal(chained, expect)

    print(f"WORKER-{proc_id}-PARITY-OK "
          f"{json.dumps(assigned.tolist())}", flush=True)


def launch(nprocs: int, devices_per_proc: int = DEVICES_PER_PROC,
           out_path: str = "") -> int:
    import socket
    import time
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--worker",
             str(i), "--procs", str(nprocs), "--port", str(port),
             "--devices-per-proc", str(devices_per_proc)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            cwd=REPO, env={**os.environ, "PYTHONPATH": REPO})
        for i in range(nprocs)]
    outs = []
    ok = True
    for i, p in enumerate(procs):
        try:
            out, err = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            p.kill()
            out, err = p.communicate()
            ok = False
        outs.append(out)
        if p.returncode != 0 or f"WORKER-{i}-PARITY-OK" not in out:
            ok = False
            print(f"worker {i} rc={p.returncode}\n{err[-2000:]}",
                  file=sys.stderr)
    # every process must agree on the bindings (the scan's argmax
    # reduced across processes — divergence means a broken collective)
    lines = [line for out in outs for line in out.splitlines()
             if "PARITY-OK" in line]
    payloads = {line.split(" ", 1)[1] for line in lines}
    if len(payloads) != 1:
        ok = False
        print(f"processes disagree: {payloads}", file=sys.stderr)
    doc = {"multihost_dryrun_ok": ok, "processes": nprocs,
           "devices_per_proc": devices_per_proc,
           "global_devices": nprocs * devices_per_proc,
           "bindings_agree_across_processes": len(payloads) == 1,
           "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())}
    if out_path:
        from kubernetes_tpu.kubemark.tpu_evidence import _atomic_write_json
        _atomic_write_json(out_path, doc)
    print(json.dumps(doc))
    return 0 if ok else 1


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--worker", type=int, default=None)
    ap.add_argument("--procs", type=int, default=4)
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--devices-per-proc", type=int,
                    default=DEVICES_PER_PROC)
    ap.add_argument("--out", default="")
    args = ap.parse_args()
    if args.worker is not None:
        worker(args.worker, args.procs, args.port,
               args.devices_per_proc)
        return 0
    return launch(args.procs, args.devices_per_proc, args.out)


if __name__ == "__main__":
    main()
