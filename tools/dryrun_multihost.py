#!/usr/bin/env python
"""Multi-HOST dryrun: the sharded scheduling scan across OS processes.

dryrun_multichip proves the single-process multi-device mesh; this
proves the DCN shape — two (or more) separate processes, each owning a
slice of the global device set, joined by jax.distributed into ONE
mesh. The batch engine's node-axis sharding then makes its per-step
argmax reduce ACROSS processes (gloo collectives on CPU, the exact
lowering slot ICI/DCN collectives fill on real multi-host TPU — the
jax.distributed + Mesh code path is identical, only the transport
differs). Bindings are asserted bit-equal to a single-process run of
the same encode.

The launcher's join is BOUNDED (a wedged worker can no longer hang it
forever): one overall deadline covers the whole worker set, the first
worker failure kills every survivor immediately, and any failure path
exits nonzero.

--fail-shard adds the shard-failure gate (ISSUE 19): a deliberately
wedged worker must be detected within the deadline and the whole set
reaped; a relaunch at the SURVIVING process shape must pass binding
parity (the survivor-restart story of a real pod losing a host); and
the in-process shard-kill soak (kubemark/shard_soak.py — lease expiry,
fence, survivor re-shard, journal replay, epoch-fenced in-flight drop)
runs in a subprocess with its verdicts embedded. MULTIHOST.json then
carries the failure-gate fields; bench.py regenerates it every round.

Launcher:  python tools/dryrun_multihost.py [--procs 4]
               [--devices-per-proc 2] [--out MULTIHOST.json]
               [--fail-shard]
Worker:    python tools/dryrun_multihost.py --worker <id> --procs N \
               --port P   (spawned by the launcher; --wedge hangs it,
               emulating a dead host for the detection gate)
"""

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DEVICES_PER_PROC = 2
#: overall worker-set deadline for a NORMAL run (compile + collectives
#: on a loaded box), and the much shorter one for the wedge-detection
#: gate (nothing useful can happen once a worker is wedged)
JOIN_DEADLINE = 300.0
WEDGE_DEADLINE = 30.0


def worker(proc_id: int, nprocs: int, port: int,
           devices_per_proc: int = DEVICES_PER_PROC,
           wedge: bool = False) -> None:
    if wedge:
        # a dead host: never joins the collective, never exits — the
        # launcher's bounded join must detect and reap the whole set
        while True:
            time.sleep(60)
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = \
        f"--xla_force_host_platform_device_count={devices_per_proc}"
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=nprocs, process_id=proc_id)

    import numpy as np
    from jax.sharding import Mesh

    from __graft_entry__ import _tiny_snapshot_inline
    from kubernetes_tpu.sched.device import BatchEngine, encode_snapshot

    n_global = jax.device_count()
    assert n_global == nprocs * devices_per_proc, n_global
    mesh = Mesh(np.array(jax.devices()), ("nodes",))
    engine = BatchEngine(mesh=mesh)
    assert engine.spans_processes

    # identical encode on every host (deterministic snapshot) — the
    # replicated-host-state model of a real multi-host scheduler
    snap = _tiny_snapshot_inline(n_nodes=2 * n_global, n_pending=12)
    enc = encode_snapshot(snap, node_pad_to=n_global)
    assigned, _state = engine.run(enc)
    assigned = np.asarray(assigned[:enc.n_pods])

    # single-process reference: same encode, no mesh, local device
    single = BatchEngine()
    expect, _ = single.run(enc)
    expect = np.asarray(expect[:enc.n_pods])
    assert np.array_equal(assigned, expect), (assigned, expect)
    assert int((assigned >= 0).sum()) > 0, "nothing scheduled"

    # the PRODUCTION pipeline path across processes: run_chunked
    # executes the pod axis as fixed-size chunks with the carry
    # threaded between dispatches as an ON-DEVICE GLOBAL array — the
    # cross-host state never round-trips through a host. Must be
    # bit-equal to the one-shot scan and to the single-process
    # chunked run.
    half = max(1, enc.n_pods // 2)
    chained, _carry = engine.run_chunked(enc, half)
    exp_chunked, _ = single.run_chunked(enc, half)
    chained = np.asarray(chained)[:enc.n_pods]
    assert np.array_equal(chained, np.asarray(exp_chunked)[:enc.n_pods])
    assert np.array_equal(chained, expect)

    print(f"WORKER-{proc_id}-PARITY-OK "
          f"{json.dumps(assigned.tolist())}", flush=True)


def _spawn_workers(nprocs: int, devices_per_proc: int,
                   wedge_worker: int = -1) -> list:
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    procs = []
    for i in range(nprocs):
        argv = [sys.executable, os.path.abspath(__file__), "--worker",
                str(i), "--procs", str(nprocs), "--port", str(port),
                "--devices-per-proc", str(devices_per_proc)]
        if i == wedge_worker:
            argv.append("--wedge")
        procs.append(subprocess.Popen(
            argv, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, cwd=REPO, env={**os.environ, "PYTHONPATH": REPO}))
    return procs


def _reap(procs: list) -> None:
    for p in procs:
        if p.poll() is None:
            p.kill()


def _join_bounded(procs: list, deadline: float):
    """Join the whole worker set under ONE deadline. The first worker
    that exits NONZERO kills every survivor on the spot (they are
    blocked in a collective their peer will never join); hitting the
    deadline kills the whole set. Returns (outs, errs, ok, timed_out)
    — outs/errs always fully collected post-kill, never blocking."""
    t0 = time.monotonic()
    ok = True
    timed_out = False
    live = list(range(len(procs)))
    while live:
        if time.monotonic() - t0 >= deadline:
            timed_out = ok = False
            _reap(procs)
            break
        for i in list(live):
            rc = procs[i].poll()
            if rc is None:
                continue
            live.remove(i)
            if rc != 0:
                # one dead worker wedges the rest mid-collective:
                # reap them now instead of waiting out the deadline
                ok = False
                _reap(procs)
        time.sleep(0.05)
    outs, errs = [], []
    for p in procs:
        out, err = p.communicate()
        outs.append(out)
        errs.append(err)
        if p.returncode != 0:
            ok = False
    return outs, errs, ok, timed_out


def _parity_run(nprocs: int, devices_per_proc: int,
                deadline: float = JOIN_DEADLINE) -> dict:
    """One full worker-set run; every process must report parity and
    agree on the bindings (the scan's argmax reduced across processes —
    divergence means a broken collective)."""
    procs = _spawn_workers(nprocs, devices_per_proc)
    outs, errs, ok, timed_out = _join_bounded(procs, deadline)
    for i, p in enumerate(procs):
        if p.returncode != 0 or f"WORKER-{i}-PARITY-OK" not in outs[i]:
            ok = False
            print(f"worker {i} rc={p.returncode}\n{errs[i][-2000:]}",
                  file=sys.stderr)
    lines = [line for out in outs for line in out.splitlines()
             if "PARITY-OK" in line]
    payloads = {line.split(" ", 1)[1] for line in lines}
    if len(payloads) != 1:
        ok = False
        print(f"processes disagree: {payloads}", file=sys.stderr)
    return {"ok": ok, "timed_out": timed_out,
            "bindings_agree_across_processes": len(payloads) == 1,
            "processes": nprocs}


def _wedge_gate(nprocs: int, devices_per_proc: int) -> dict:
    """Kill-detection gate: worker nprocs-1 wedges (a dead host), the
    rest block in jax.distributed waiting for it. The bounded join
    must detect the hang within WEDGE_DEADLINE and reap the whole set."""
    t0 = time.monotonic()
    procs = _spawn_workers(nprocs, devices_per_proc,
                           wedge_worker=nprocs - 1)
    _outs, _errs, ok, timed_out = _join_bounded(procs, WEDGE_DEADLINE)
    reaped = all(p.poll() is not None for p in procs)
    return {"wedged_worker": nprocs - 1,
            "detected": (not ok),
            "detected_within_s": round(time.monotonic() - t0, 1),
            "deadline_s": WEDGE_DEADLINE,
            "survivors_reaped": reaped,
            "launcher_exit_nonzero": not ok}


def _embedded_soak() -> dict:
    """The in-process shard-kill soak (virtual 8-device mesh, FakeClock
    lease expiry) in a subprocess with a controlled device env; its
    verdicts are the lease/epoch/replay half of the failure gate."""
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "kubernetes_tpu.kubemark.shard_soak"],
            capture_output=True, text=True, timeout=JOIN_DEADLINE,
            cwd=REPO, env={**os.environ, "PYTHONPATH": REPO})
        for line in reversed(proc.stdout.splitlines()):
            line = line.strip()
            if line.startswith("{"):
                return json.loads(line)
        return {"converged": False, "detail": proc.stderr[-500:]}
    except Exception as e:
        return {"converged": False, "detail": str(e)[:500]}


def launch(nprocs: int, devices_per_proc: int = DEVICES_PER_PROC,
           out_path: str = "", fail_shard: bool = False) -> int:
    run = _parity_run(nprocs, devices_per_proc)
    ok = run["ok"]
    doc = {"multihost_dryrun_ok": ok, "processes": nprocs,
           "devices_per_proc": devices_per_proc,
           "global_devices": nprocs * devices_per_proc,
           "bindings_agree_across_processes":
               run["bindings_agree_across_processes"],
           "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())}
    if fail_shard:
        wedge = _wedge_gate(nprocs, devices_per_proc)
        # a host died: the launcher relaunches at the surviving shape —
        # the mesh-size-invariance parity inside each worker is exactly
        # the re-shard parity claim at DCN scale
        survivor = _parity_run(max(1, nprocs - 1), devices_per_proc)
        soak = _embedded_soak()
        gate_ok = (wedge["detected"] and wedge["survivors_reaped"]
                   and survivor["ok"]
                   and bool(soak.get("converged")))
        doc["shard_failure"] = {
            "gate_ok": gate_ok,
            "wedge": wedge,
            "survivor_shape": {
                "processes": survivor["processes"],
                "parity_ok": survivor["ok"],
                "bindings_agree_across_processes":
                    survivor["bindings_agree_across_processes"]},
            "soak": soak}
        ok = doc["multihost_dryrun_ok"] = ok and gate_ok
    if out_path:
        from kubernetes_tpu.kubemark.tpu_evidence import _atomic_write_json
        _atomic_write_json(out_path, doc)
    print(json.dumps(doc))
    return 0 if ok else 1


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--worker", type=int, default=None)
    ap.add_argument("--procs", type=int, default=4)
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--devices-per-proc", type=int,
                    default=DEVICES_PER_PROC)
    ap.add_argument("--out", default="")
    ap.add_argument("--wedge", action="store_true")
    ap.add_argument("--fail-shard", action="store_true")
    args = ap.parse_args()
    if args.worker is not None:
        worker(args.worker, args.procs, args.port,
               args.devices_per_proc, wedge=args.wedge)
        return 0
    return launch(args.procs, args.devices_per_proc, args.out,
                  fail_shard=args.fail_shard)


if __name__ == "__main__":
    # the satellite-1 contract: any failure path exits NONZERO (the old
    # entry dropped main()'s status on the floor)
    raise SystemExit(main())
