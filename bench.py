"""Headline benchmark: kubemark-scale scheduler throughput.

Scenario (BASELINE.json north star): 30k pending pods onto 5k hollow
nodes, full default predicate/priority set, one service so selector
spreading engages. The reference's serial scheduler is rate-limited to 50
binds/s by default (plugin/cmd/kube-scheduler/app/server.go:69-70) and
benchmarked at 1000-node scale (test/integration/scheduler_test.go:278);
vs_baseline is measured pods/sec over that 50/s default sustained rate.

Wall-clock includes host-side snapshot encoding + device transfer + the
scanned schedule + assignment fetch; XLA compile is excluded by a warmup
run on identical shapes (compile caches persist in a live scheduler).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import argparse
import json
import sys
import time


def build_snapshot(n_nodes, n_pods):
    from kubernetes_tpu.core import types as api
    from kubernetes_tpu.core.quantity import Quantity
    from kubernetes_tpu.sched.device import ClusterSnapshot

    gi = 1024 ** 3
    mi = 1024 ** 2
    # node shape from the reference's BenchmarkScheduling fixture:
    # 4 CPU / 32Gi / 32-pod cap (test/integration/scheduler_test.go:329-354),
    # pod cap raised to kubemark density (hollow_kubelet.go MaxPods=40)
    nodes = [
        api.Node(
            metadata=api.ObjectMeta(name=f"node-{i:05d}",
                                    labels={"zone": f"z{i % 8}"}),
            status=api.NodeStatus(capacity={
                "cpu": Quantity(4000),
                "memory": Quantity(32 * gi * 1000),
                "pods": Quantity(40 * 1000)}))
        for i in range(n_nodes)]
    services = [api.Service(
        metadata=api.ObjectMeta(name="web", namespace="default"),
        spec=api.ServiceSpec(selector={"app": "web"}))]
    pods = [
        api.Pod(
            metadata=api.ObjectMeta(name=f"pod-{j:06d}", namespace="default",
                                    labels={"app": "web"}),
            spec=api.PodSpec(containers=[api.Container(
                name="c", image="img",
                resources=api.ResourceRequirements(requests={
                    "cpu": Quantity(100),
                    "memory": Quantity(500 * mi * 1000)}))]))
        for j in range(n_pods)]
    return ClusterSnapshot(nodes=nodes, services=services, pending_pods=pods)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=5000)
    ap.add_argument("--pods", type=int, default=30000)
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args()

    from kubernetes_tpu.sched.device import BatchEngine, encode_snapshot

    snap = build_snapshot(args.nodes, args.pods)
    engine = BatchEngine()

    # warmup: same shapes -> XLA compile cache hot
    t0 = time.time()
    enc = encode_snapshot(snap, node_pad_to=engine.n_shards)
    t_encode = time.time() - t0
    assigned, _ = engine.run(enc)
    t_warm = time.time() - t0
    unbound = int((assigned[:enc.n_pods] < 0).sum())
    if args.verbose:
        print(f"# encode {t_encode:.2f}s warm-total {t_warm:.2f}s "
              f"unbound {unbound}", file=sys.stderr)

    # measured run: encode + transfer + schedule + fetch
    t0 = time.time()
    enc = encode_snapshot(snap, node_pad_to=engine.n_shards)
    assigned, _ = engine.run(enc)
    elapsed = time.time() - t0

    n_bound = int((assigned[:enc.n_pods] >= 0).sum())
    pods_per_sec = n_bound / elapsed
    print(json.dumps({
        "metric": "scheduler_throughput_5k_nodes",
        "value": round(pods_per_sec, 1),
        "unit": "pods/sec",
        "vs_baseline": round(pods_per_sec / 50.0, 1)}))


if __name__ == "__main__":
    main()
