"""Headline benchmark: kubemark-scale END-TO-END scheduler throughput.

Scenario (BASELINE.json north star): 30k pending pods onto 5k hollow
nodes, default predicate/priority set. The headline number is the full
pipeline — registry + watch fan-out + FIFO drain + incremental encode +
device scan + batched CAS binding commit + hollow-fleet confirmation —
i.e. kubemark's BenchmarkScheduling (test/integration/scheduler_test.go:278)
at 5x the reference's 1000-node fixture, with 30 concurrent pod writers.
The engine-only scoring rate (what the device scan alone sustains) is
reported alongside.

The reference's serial scheduler is rate-limited to 50 binds/s by default
(plugin/cmd/kube-scheduler/app/server.go:69-70); vs_baseline is measured
end-to-end pods/sec over that 50/s default sustained rate.

XLA compiles are excluded by warmup at identical shapes (a live scheduler
process has warm caches; the reference benchmark likewise measures a warm
in-process scheduler).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
"""

import argparse
import json
import os
import subprocess
import sys
import time

_PLATFORM_ENV = "KTPU_BENCH_PLATFORM_CHECKED"


def _ensure_live_platform() -> str:
    """The default platform may be a tunneled TPU; a wedged tunnel hangs
    the first dispatch forever. Probe it in a subprocess with a timeout
    and fall back to CPU (recorded in the output) rather than hang the
    benchmark run."""
    if os.environ.get(_PLATFORM_ENV):
        import jax
        plat = os.environ.get("JAX_PLATFORMS", "")
        if plat:  # honor the fallback past any sitecustomize pin
            jax.config.update("jax_platforms", plat)
        return "cpu-fallback" if plat == "cpu" else "default"
    probe = ("import jax, jax.numpy as jnp; "
             "jnp.ones(4).sum().block_until_ready(); print('ok')")
    try:
        ok = subprocess.run(
            [sys.executable, "-c", probe], capture_output=True,
            timeout=180).returncode == 0
    except subprocess.TimeoutExpired:
        ok = False
    os.environ[_PLATFORM_ENV] = "1"
    if not ok:
        env = {**os.environ, "JAX_PLATFORMS": "cpu"}
        os.execve(sys.executable,
                  [sys.executable, os.path.abspath(__file__)]
                  + sys.argv[1:], env)
    return "default"


def engine_only(n_nodes, n_pods):
    """Device scan throughput on a prebuilt snapshot (encode excluded:
    the live pipeline encodes incrementally, measured by the e2e number)."""
    from kubernetes_tpu.core import types as api
    from kubernetes_tpu.core.quantity import Quantity
    from kubernetes_tpu.sched.device import (BatchEngine, ClusterSnapshot,
                                             encode_snapshot)

    gi = 1024 ** 3
    mi = 1024 ** 2
    # node shape from the reference's BenchmarkScheduling fixture:
    # 4 CPU / 32Gi (test/integration/scheduler_test.go:329-354), pod cap
    # raised to kubemark density (hollow_kubelet.go MaxPods=40)
    nodes = [
        api.Node(
            metadata=api.ObjectMeta(name=f"node-{i:05d}",
                                    labels={"zone": f"z{i % 8}"}),
            status=api.NodeStatus(capacity={
                "cpu": Quantity(4000),
                "memory": Quantity(32 * gi * 1000),
                "pods": Quantity(40 * 1000)}))
        for i in range(n_nodes)]
    services = [api.Service(
        metadata=api.ObjectMeta(name="web", namespace="default"),
        spec=api.ServiceSpec(selector={"app": "web"}))]
    pods = [
        api.Pod(
            metadata=api.ObjectMeta(name=f"pod-{j:06d}", namespace="default",
                                    labels={"app": "web"}),
            spec=api.PodSpec(containers=[api.Container(
                name="c", image="img",
                resources=api.ResourceRequirements(requests={
                    "cpu": Quantity(100),
                    "memory": Quantity(500 * mi * 1000)}))]))
        for j in range(n_pods)]
    snap = ClusterSnapshot(nodes=nodes, services=services, pending_pods=pods)
    engine = BatchEngine()
    enc = encode_snapshot(snap, node_pad_to=engine.n_shards,
                          pod_pad_to=((n_pods + 8191) // 8192) * 8192)
    # chunked at the production tile shape: one compiled [8192] program
    # (a single 30k-step scan would compile for minutes on the CPU
    # fallback platform) and the same dispatch granularity the live
    # scheduler uses
    assigned, _ = engine.run_chunked(enc, 8192)   # warmup compile
    t0 = time.time()
    assigned, _ = engine.run_chunked(enc, 8192)
    elapsed = time.time() - t0
    n_bound = int((assigned[:enc.n_pods] >= 0).sum())
    return n_bound / elapsed, n_bound


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=5000)
    ap.add_argument("--pods", type=int, default=30000)
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args()

    platform = _ensure_live_platform()
    from kubernetes_tpu.kubemark.benchmark import run_scheduling_benchmark

    r = run_scheduling_benchmark(args.nodes, args.pods, "batch")
    if args.verbose:
        print(f"# e2e {r.scheduled}/{r.n_pods} in {r.elapsed_s:.2f}s",
              file=sys.stderr)
    engine_rate, _ = engine_only(args.nodes, args.pods)

    print(json.dumps({
        "metric": "e2e_scheduling_throughput_5k_nodes",
        "value": round(r.pods_per_sec, 1),
        "unit": "pods/sec",
        "vs_baseline": round(r.pods_per_sec / 50.0, 1),
        "e2e_elapsed_s": round(r.elapsed_s, 2),
        "scheduled": r.scheduled,
        "nodes": r.n_nodes,
        "pods": r.n_pods,
        "engine_only_pods_per_sec": round(engine_rate, 1),
        "platform": platform}))


if __name__ == "__main__":
    main()
