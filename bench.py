"""Headline benchmark: kubemark-scale END-TO-END scheduler throughput.

Scenario (BASELINE.json north star): 30k pending pods onto 5k hollow
nodes, default predicate/priority set. The headline number is the full
pipeline — registry + watch fan-out + FIFO drain + incremental encode +
device scan + batched CAS binding commit + hollow-fleet confirmation —
i.e. kubemark's BenchmarkScheduling (test/integration/scheduler_test.go:278)
at 5x the reference's 1000-node fixture, with 30 concurrent pod writers.
The engine-only scoring rate (what the device scan alone sustains) is
reported alongside, as are the density SLO percentiles
(kubernetes_tpu/kubemark/slo.py; ref test/e2e/metrics_util.go:41-47,
density.go:203-208) and the Pallas-filter health on real hardware.

The reference's serial scheduler is rate-limited to 50 binds/s by default
(plugin/cmd/kube-scheduler/app/server.go:69-70); vs_baseline is measured
end-to-end pods/sec over that 50/s default sustained rate.

XLA compiles are excluded by warmup at identical shapes (a live scheduler
process has warm caches; the reference benchmark likewise measures a warm
in-process scheduler).

Prints ONE JSON line. Stable schema (r03+): metric, value, unit,
vs_baseline, e2e_elapsed_s, scheduled, nodes, pods,
engine_only_pods_per_sec, platform, probe, pallas, slo; r04 adds tpu
(opportunistic real-hardware evidence merged from tools/tpu_watch.py)
and e2e_runs (value = best of two on a ±20%-noise shared host; both
raw runs recorded); r05 adds multihost (the 4-process x 2-device DCN
dryrun regenerated per round) and, when the headline ran on the real
tpu backend at the north-star shape, folds its e2e/engine numbers
into TPU_EVIDENCE_BEST.json under the shared chip lock; r06 adds
node_chaos (the --node-kill-fraction recovery arm: kill/convergence
times, evictions, rebinds, the zero-dead-bindings gate), null unless
requested; r07 adds durability (the --wal-dir fsync-policy A/B +
recovery replay, and the --crash-seed process-crash soak: recovery
wall-clock, replayed records/s, leader transitions, the
zero-duplicate-bindings / one-holder-per-term gates), null unless
requested; r08 adds workload (the --workload-seed trace-replay soak:
a compressed day of diurnal/burst/jobwave/rollout/churn traffic under
5% API faults + a 10% node-kill plan, recording per-phase bind
throughput and every SLO verdict), null unless requested; r09 adds
lint (orchlint wall time over the tree and its verdict — recorded
every round so the static-analysis pass stays inside its 5s tier-1
budget as rules and tree both grow); r10 adds pipeline (the --txn-ab
multi-key-transaction A/B: the headline arm commits each bind tile /
status burst as ONE store.commit_txn revision window while the
control arm restores the per-1024-op store.batch() chunk loops),
null unless requested; r11 adds obs (the --trace causal-tracing arm:
one traced pass recording the pod-lifecycle stage decomposition —
per-stage p50/p99 from pod_e2e_stage_seconds plus the
stage-coverage-of-e2e-wall ratio, gated >=90% — and one tracing-off
control pass gating the tracer's throughput cost at <5%), null
unless requested; r12 adds multichip (the --mesh-devices scaling
ladder: engine-only passes on 1/2/4/../N virtual-device meshes with
the node axis sharded, per-rung pods/s + per-chip scaling efficiency
+ the mesh-vs-single-device bit-equality gate, and with
--density-ladder the 20k-node / 150k-pod density tier written to
DENSITY_20K.json), null unless requested; r13 adds serving (the
--watch-fanout arm: the N-worker apiserver fan-out storm —
create-storm throughput, per-worker delivery lag p50/p99, the
watch-deliver burn-rate SLO verdict, and the 1-vs-N scaling readout
with its 1-core overlap-witness caveat — with the SLO timeline also
written to SLO_10KWATCH.json), null unless requested.
"""

import argparse
import calendar
import json
import os
import subprocess
import sys
import time


def _pallas_status(platform: str) -> dict:
    """On real hardware, compile + run the Pallas predicate filter under
    Mosaic in a bounded subprocess and record the outcome (the kernel
    must prove itself on the TPU, not only in interpret mode); off-TPU
    report why it was skipped."""
    if platform != "default":
        return {"status": "skipped", "reason": "cpu-fallback platform"}
    prog = (
        "import numpy as np\n"
        "from kubernetes_tpu.sched.device import (BatchEngine,"
        " encode_snapshot)\n"
        "from kubernetes_tpu.sched.device import pallas_filter\n"
        "from __graft_entry__ import _tiny_snapshot_inline\n"
        "enc = encode_snapshot(_tiny_snapshot_inline(8, 16))\n"
        "assert pallas_filter.supports(enc), 'layout unsupported'\n"
        "masks = pallas_filter.filter_masks(enc)\n"
        "ref, _ = BatchEngine().probe(enc)\n"
        "ok = np.array_equal(np.asarray(masks),"
        " np.asarray(ref[:enc.n_pods]).astype(bool))\n"
        "print('PALLAS-OK' if ok else 'PALLAS-MISMATCH')\n")
    try:
        res = subprocess.run(
            [sys.executable, "-c", prog], capture_output=True, text=True,
            timeout=300, cwd=os.path.dirname(os.path.abspath(__file__)))
    except subprocess.TimeoutExpired:
        return {"status": "timeout"}
    if "PALLAS-OK" in res.stdout:
        return {"status": "ran", "parity": True}
    if "PALLAS-MISMATCH" in res.stdout:
        return {"status": "ran", "parity": False}
    return {"status": "error", "tail": (res.stdout + res.stderr)[-400:]}


def _await_capture_lock(max_wait: float = 300.0) -> None:
    """If the opportunistic evidence capture (tools/tpu_watch.py) is
    mid-run, wait for it to release the one tunneled chip rather than
    measure under contention; stale locks (>45 min) are ignored."""
    from kubernetes_tpu.kubemark.tpu_evidence import foreign_chip_lock_fresh
    deadline = time.time() + max_wait
    while time.time() < deadline and foreign_chip_lock_fresh():
        time.sleep(5)


def _tpu_section():
    """Merge the freshest opportunistic real-TPU evidence (captured by
    tools/tpu_watch.py whenever the flaky tunnel opened mid-round) plus
    a summary of the round's probe log — so the artifact carries real
    hardware numbers even when the end-of-round probe fails, or proof
    that the tunnel never opened."""
    here = os.path.dirname(os.path.abspath(__file__))
    out = {}
    try:
        with open(os.path.join(here, "TPU_EVIDENCE.json")) as f:
            out["evidence"] = json.load(f)
    except (OSError, ValueError):
        out["evidence"] = None
    # per-section best across captures (cumulative; every entry stamped
    # with its source-capture ts) — the demonstrated ceiling alongside
    # the freshest run; best_stale below marks whether any capture this
    # round actually contributed
    try:
        with open(os.path.join(here, "TPU_EVIDENCE_BEST.json")) as f:
            out["best"] = json.load(f)
    except (OSError, ValueError):
        out["best"] = None
    # summarize only the LATEST watcher run (each round starts a fresh
    # watcher, which logs an {"event": "start"} record) so a prior
    # round's probes/evidence can't masquerade as this round's
    probes = {"total": 0, "healthy": 0, "first_ts": None, "last_ts": None,
              "watcher_start_ts": None, "errors": 0}
    try:
        with open(os.path.join(here, "TPU_PROBES.jsonl")) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                ev = rec.get("event")
                if ev == "start":
                    probes.update(total=0, healthy=0, first_ts=None,
                                  last_ts=None, errors=0,
                                  watcher_start_ts=rec.get("ts"))
                elif ev == "probe":
                    probes["total"] += 1
                    probes["healthy"] += 1 if rec.get("ok") else 0
                    probes["first_ts"] = probes["first_ts"] or rec.get("ts")
                    probes["last_ts"] = rec.get("ts")
                elif ev == "error":
                    probes["errors"] += 1
    except OSError:
        pass
    out["probes"] = probes
    # staleness is relative to the LATEST watcher instance; a mid-round
    # driver restart starts a fresh watcher, so a same-round capture
    # from before the restart reads "stale" — the age fields
    # disambiguate (hours-old ≠ last-round-old)
    def _age_s(ts: str):
        try:
            return round(time.time() - calendar.timegm(
                time.strptime(ts, "%Y-%m-%dT%H:%M:%SZ")), 1)
        except (ValueError, TypeError):
            return None

    if out["evidence"] is not None and probes["watcher_start_ts"]:
        out["evidence_stale"] = (
            out["evidence"].get("ts_start", "") < probes["watcher_start_ts"])
        out["evidence_age_s"] = _age_s(
            out["evidence"].get("ts_start", ""))
    if out["best"] is not None and probes["watcher_start_ts"]:
        out["best_stale"] = (
            out["best"].get("ts_updated", "") < probes["watcher_start_ts"])
        out["best_age_s"] = _age_s(out["best"].get("ts_updated", ""))
    return out


def _engine_snapshot(n_nodes, n_pods, plain=False):
    """The engine-only fixture: kubemark-shape nodes + homogeneous web
    pods, shared by engine_only() and the multichip ladder children so
    every rung scores the same problem."""
    from kubernetes_tpu.core import types as api
    from kubernetes_tpu.core.quantity import Quantity
    from kubernetes_tpu.sched.device import ClusterSnapshot

    gi = 1024 ** 3
    mi = 1024 ** 2
    # node shape from the reference's BenchmarkScheduling fixture:
    # 4 CPU / 32Gi (test/integration/scheduler_test.go:329-354), pod cap
    # raised to kubemark density (hollow_kubelet.go MaxPods=40)
    nodes = [
        api.Node(
            metadata=api.ObjectMeta(name=f"node-{i:05d}",
                                    labels={"zone": f"z{i % 8}"}),
            status=api.NodeStatus(capacity={
                "cpu": Quantity(4000),
                "memory": Quantity(32 * gi * 1000),
                "pods": Quantity(40 * 1000)}))
        for i in range(n_nodes)]
    services = [api.Service(
        metadata=api.ObjectMeta(name="web", namespace="default"),
        spec=api.ServiceSpec(selector={"app": "web"}))]
    pods = [
        api.Pod(
            metadata=api.ObjectMeta(name=f"pod-{j:06d}", namespace="default",
                                    labels={"app": "web"}),
            spec=api.PodSpec(containers=[api.Container(
                name="c", image="img",
                resources=api.ResourceRequirements(requests={
                    "cpu": Quantity(100),
                    "memory": Quantity(500 * mi * 1000)}))]))
        for j in range(n_pods)]
    if plain:
        services = []
        for p in pods:
            p.metadata.labels = {}
    return ClusterSnapshot(nodes=nodes, services=services,
                           pending_pods=pods)


def engine_only(n_nodes, n_pods, plain=False, speculative=None):
    """Device scan throughput on a prebuilt snapshot (encode excluded:
    the live pipeline encodes incrementally, measured by the e2e number).

    plain=True drops the service so the batch runs the node-local tier —
    the tier the live e2e pipeline actually executes (its bench pods
    have no services/RCs) and the one where the speculative engine
    engages; `speculative` pins the engine choice for A/B runs
    (None = the engine's platform default)."""
    from kubernetes_tpu.sched.device import BatchEngine, encode_snapshot

    snap = _engine_snapshot(n_nodes, n_pods, plain=plain)
    engine = BatchEngine(speculative=speculative)
    enc = encode_snapshot(snap, node_pad_to=engine.n_shards,
                          pod_pad_to=((n_pods + 8191) // 8192) * 8192)
    # chunked at the production tile shape: one compiled [8192] program
    # (a single 30k-step scan would compile for minutes on the CPU
    # fallback platform) and the same dispatch granularity the live
    # scheduler uses
    assigned, _ = engine.run_chunked(enc, 8192)   # warmup compile
    t0 = time.time()
    assigned, _ = engine.run_chunked(enc, 8192)
    elapsed = time.time() - t0
    n_bound = int((assigned[:enc.n_pods] >= 0).sum())
    return n_bound / elapsed, n_bound


# the ladder rung shape: big enough that the scan dominates (not the
# encode) and spans two 8192-pod tiles so the device-carry chain runs,
# small enough that a 4-rung ladder stays in minutes on the cpu box
_LADDER_NODES = 2000
_LADDER_PODS = 16384


def _mesh_ladder_child(n_devices, n_nodes, n_pods, tile=8192):
    """Subprocess body for one multichip ladder rung: an engine-only
    scoring pass with the node axis sharded over an n-device mesh
    (virtual CPU devices forced by the parent's XLA_FLAGS), gated
    bit-equal against the single-device engine at the same shape.
    Prints one 'LADDER {json}' line for the parent to collect."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    from jax.sharding import Mesh

    from kubernetes_tpu.sched.device import BatchEngine, encode_snapshot

    snap = _engine_snapshot(n_nodes, n_pods)
    if n_devices > 1:
        mesh = Mesh(np.asarray(jax.devices()[:n_devices]), ("nodes",))
        engine = BatchEngine(mesh=mesh)
    else:
        engine = BatchEngine()
    enc = encode_snapshot(snap, node_pad_to=engine.n_shards,
                          pod_pad_to=((n_pods + tile - 1) // tile) * tile)
    engine.run_chunked(enc, tile)   # warmup compile
    t0 = time.time()
    assigned, _ = engine.run_chunked(enc, tile)
    elapsed = time.time() - t0
    a = np.asarray(assigned[:enc.n_pods])
    out = {"n_devices": n_devices, "nodes": n_nodes, "pods": n_pods,
           "bound": int((a >= 0).sum()),
           "pods_per_sec": round(n_pods / elapsed, 1),
           "elapsed_s": round(elapsed, 3)}
    if n_devices > 1:
        # the bit-equality gate: the sharded scan must bind every pod
        # to the same node the single-device engine picks (the serial
        # oracle is infeasible at the density tier; single-device is
        # itself oracle-gated by tests/test_device_parity.py)
        ref, _ = BatchEngine().run_chunked(enc, tile)
        out["parity_vs_single_device"] = bool(
            np.array_equal(a, np.asarray(ref[:enc.n_pods])))
    print("LADDER " + json.dumps(out), flush=True)


def _ladder_rung(n_devices, n_nodes, n_pods, timeout):
    """Run one ladder rung in a subprocess with n forced host devices
    (same virtual-device pattern as __graft_entry__.dryrun_multichip:
    the parent process's jax is already initialized with one device, so
    the count must be forced before the child's first jax import)."""
    repo = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    env["XLA_FLAGS"] = (
        f"{flags} --xla_force_host_platform_device_count={n_devices}"
    ).strip()
    prog = (
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "import bench\n"
        f"bench._mesh_ladder_child({n_devices}, {n_nodes}, {n_pods})\n")
    try:
        res = subprocess.run(
            [sys.executable, "-c", prog], env=env, capture_output=True,
            text=True, timeout=timeout, cwd=repo)
    except subprocess.TimeoutExpired:
        return {"n_devices": n_devices, "nodes": n_nodes, "pods": n_pods,
                "error": f"timeout after {timeout}s"}
    for line in reversed(res.stdout.splitlines()):
        if line.startswith("LADDER "):
            return json.loads(line[len("LADDER "):])
    return {"n_devices": n_devices, "nodes": n_nodes, "pods": n_pods,
            "error": (res.stdout + res.stderr)[-500:],
            "rc": res.returncode}


def _multichip_section(args):
    """The --mesh-devices arm: the 1/2/4/../N virtual-device scaling
    ladder at a fixed engine-only shape (per-rung pods/s, per-chip
    scaling efficiency vs the 1-device rung, and the mesh-vs-single-
    device bit-equality gate), plus — under --density-ladder — the
    20k-node / 150k-pod density tier on the full mesh, written to
    DENSITY_20K.json. Virtual devices share the one physical core, so
    efficiency here measures partitioning overhead, not speedup; on
    real chips the same ladder reads scaling."""
    ladder_ns, n = [], 1
    while n <= args.mesh_devices:
        ladder_ns.append(n)
        n *= 2
    rungs = [_ladder_rung(n, _LADDER_NODES, _LADDER_PODS, timeout=900)
             for n in ladder_ns]
    base = next((r.get("pods_per_sec") for r in rungs
                 if r.get("n_devices") == 1), None)
    for r in rungs:
        if base and r.get("pods_per_sec"):
            # per-chip efficiency: 1.0 = perfect linear scaling
            r["scaling_efficiency"] = round(
                r["pods_per_sec"] / (r["n_devices"] * base), 3)
    section = {
        "ladder_nodes": _LADDER_NODES,
        "ladder_pods": _LADDER_PODS,
        "ladder": rungs,
        "parity_ok": all(r.get("parity_vs_single_device", True)
                         for r in rungs),
        "density": None}
    if args.density_ladder:
        dn = max(2, args.mesh_devices)
        density = _ladder_rung(dn, 20000, 150000, timeout=5400)
        section["density"] = density
        section["parity_ok"] = (section["parity_ok"] and
                                density.get("parity_vs_single_device",
                                            False))
        if "error" not in density:
            from kubernetes_tpu.kubemark.tpu_evidence import \
                _atomic_write_json
            repo = os.path.dirname(os.path.abspath(__file__))
            _atomic_write_json(
                os.path.join(repo, "DENSITY_20K.json"),
                {"metric": "density_20k_nodes_150k_pods",
                 "platform": "cpu-pinned virtual mesh",
                 "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                     time.gmtime()),
                 **density})
    return section


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=5000)
    ap.add_argument("--pods", type=int, default=30000)
    ap.add_argument("--probe-attempts", type=int, default=2)
    ap.add_argument("--skip-slo", action="store_true")
    ap.add_argument("--store-ab", action="store_true",
                    help="run one extra e2e pass with watch fan-out "
                         "held under the store's ledger lock (the "
                         "pre-two-phase commit path) and report both")
    ap.add_argument("--txn-ab", action="store_true",
                    help="run one extra e2e pass with multi-key "
                         "transactions disabled (per-1024-op "
                         "store.batch() chunks, the pre-txn commit "
                         "shape) and report both arms in the "
                         "pipeline section")
    ap.add_argument("--trace", action="store_true",
                    help="run the causal-tracing A/B arm: one e2e pass "
                         "with a fresh seeded obs tracer (recording the "
                         "per-stage latency decomposition and the "
                         "stage-coverage ratio against that pass's e2e "
                         "wall) and one pass with tracing disabled (the "
                         "overhead control); records the obs section")
    ap.add_argument("--trace-seed", type=int, default=0,
                    help="seed for the --trace arm's tracer (span ids "
                         "are a pure function of seed + counter)")
    ap.add_argument("--chaos-seed", type=int, default=None,
                    help="also record one e2e pass under the seeded "
                         "chaos injector (chaos.ChaosClient, "
                         "--chaos-rate faults on every verb) — the "
                         "throughput-under-fault-load arm; the "
                         "headline number stays fault-free")
    ap.add_argument("--chaos-rate", type=float, default=0.01,
                    help="per-verb injected fault probability for the "
                         "--chaos-seed arm (default 0.01)")
    ap.add_argument("--node-kill-fraction", type=float, default=0.0,
                    help="also run the node-kill soak: this fraction "
                         "of a 1k-node hollow fleet is hard-killed "
                         "mid-run under 5%% API faults and the run "
                         "gates on convergence off the dead nodes "
                         "(kubemark/node_chaos.py); records the "
                         "node_chaos section")
    ap.add_argument("--node-kill-seed", type=int, default=0,
                    help="seed for the node-kill arm's NodeFaultPlan "
                         "and API-fault schedule (same seed -> "
                         "identical kill set)")
    ap.add_argument("--wal-dir", default=None,
                    help="run the WAL durability arm: a create storm "
                         "against a WAL-backed store under each fsync "
                         "policy (always vs batch) plus a recovery "
                         "replay, recorded as durability.wal "
                         "(kubemark/crash_soak.run_wal_bench). The "
                         "directory is used as scratch; pass a path "
                         "on the filesystem whose fsync cost you "
                         "want measured")
    ap.add_argument("--wal-records", type=int, default=5000,
                    help="record count for the --wal-dir arm")
    ap.add_argument("--crash-seed", type=int, default=None,
                    help="run the process-crash soak: WAL-backed "
                         "store, redundant schedulers + controller-"
                         "managers under lease election, 5%% API "
                         "faults, seeded apiserver/scheduler/"
                         "controller-manager kills "
                         "(kubemark/crash_soak.py); records "
                         "durability.crash — recovery wall-clock and "
                         "replayed records, leader transitions, and "
                         "the zero-duplicate-bindings / one-holder-"
                         "per-term gates")
    ap.add_argument("--workload-seed", type=int, default=None,
                    help="run the trace-replay workload soak: a "
                         "seeded, time-compressed day of heterogeneous "
                         "traffic (diurnal HPA demand, flash crowds, "
                         "Job waves, rollout steps, Service churn) "
                         "under 5%% API faults + a 10%% node-kill "
                         "plan (kubemark/workload_soak.py); records "
                         "the workload section — per-phase bind "
                         "throughput and every SLO verdict")
    ap.add_argument("--workload-trace", choices=("fast", "day"),
                    default="fast",
                    help="trace shape for the --workload-seed arm: "
                         "'fast' = 12 ticks on a small fleet (the "
                         "tier-1 gate's shape), 'day' = 48 ticks on "
                         "a 1k-node fleet (the slow gate's shape)")
    ap.add_argument("--flash-drain", action="store_true",
                    help="run the flash-crowd drain soak (ISSUE 20): "
                         "low-priority batch fills saturate a small "
                         "fleet, then a high-priority surge lands and "
                         "must preempt them — under 5%% API faults + "
                         "a 10%% node kill; records the preemption "
                         "section (surge bind p50/p99, victims, the "
                         "post-hoc wrongful-eviction audit and the "
                         "replayable surge TRIP/CLEAR timeline)")
    ap.add_argument("--flash-drain-seed", type=int, default=3,
                    help="seed for the --flash-drain arm (plan, "
                         "faults, kill set and preemption backoff "
                         "jitter all derive from it)")
    ap.add_argument("--timeseries", action="store_true",
                    help="run the metrics-plane arm: the fast workload "
                         "soak with the deterministic FleetScraper + "
                         "burn-rate evaluator on (recording the full "
                         "time-series export and the alert timeline), "
                         "plus a scraped-vs-unscraped e2e A/B (the "
                         "scrape-overhead control); records the "
                         "metricsplane section — feed the artifact to "
                         "tools/obs_report.py")
    ap.add_argument("--watch-fanout", type=int, default=None,
                    help="run the serving-plane fan-out soak: this "
                         "many concurrent watchers sharded across "
                         "--fanout-workers apiserver workers over one "
                         "shared store, under a pod create-storm "
                         "(kubemark/fanout_soak.py); records the "
                         "serving section and writes the watch-deliver "
                         "SLO timeline to SLO_10KWATCH.json")
    ap.add_argument("--fanout-workers", type=int, default=4,
                    help="worker count for the --watch-fanout arm "
                         "(a 1-worker baseline arm of the same storm "
                         "runs first for the scaling readout)")
    ap.add_argument("--mesh-devices", type=int, default=None,
                    help="run the multichip scaling ladder: engine-only "
                         "passes on 1/2/4/../N virtual-device meshes "
                         "(node axis sharded, argmax over ICI), each "
                         "mesh rung gated bit-equal to the single-"
                         "device engine; records the multichip section")
    ap.add_argument("--density-ladder", action="store_true",
                    help="with --mesh-devices: add the 20k-node / "
                         "150k-pod density tier on the full mesh "
                         "(bit-equality gated) and write DENSITY_20K."
                         "json")
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args()

    # orchlint wall time: the lint suite runs inside tier-1, so its
    # cost is part of every build — record it each round and keep it
    # under the 5s budget (it is ~1s at 155 files; a rule that regexes
    # its way to 10s would silently tax every CI run otherwise)
    from kubernetes_tpu.lint import run_lint
    lint_report = run_lint()
    lint_section = {
        "ok": lint_report.ok,
        "files": lint_report.files_scanned,
        "known_sites": len(lint_report.violations),
        "seconds": round(lint_report.seconds, 3),
        "budget_s": 5.0,
        "within_budget": lint_report.seconds < 5.0,
    }

    from kubernetes_tpu.utils.platform import ensure_live_platform
    platform, probe = ensure_live_platform(attempts=args.probe_attempts)
    _await_capture_lock()
    # hold the lock for the whole headline run so the round-long watcher
    # (tools/tpu_watch.py) defers its next opportunistic capture instead
    # of contending for the one chip mid-measurement; released at exit
    # (ownership-checked: a late-finishing capture cannot delete our
    # hold, nor we a lock another process has since written). If a
    # capture still holds the lock after the bounded wait, proceed
    # WITHOUT taking it — never stomp a live holder's record.
    import atexit
    import threading
    from kubernetes_tpu.kubemark.tpu_evidence import (refresh_chip_lock,
                                                      release_chip_lock,
                                                      try_acquire_chip_lock)
    if try_acquire_chip_lock(who="bench"):
        atexit.register(release_chip_lock)
        # heartbeat: a slow run (wedged tunnel, slow SLO sweep) must not
        # age past the 45-min staleness window and lose the chip to the
        # watcher's reclaim mid-measurement
        hb_stop = threading.Event()

        def _hb():
            while not hb_stop.wait(600.0):
                refresh_chip_lock()
        hb_thread = threading.Thread(target=_hb,
                                     name="chip-lock-heartbeat",
                                     daemon=True)
        hb_thread.start()

        def _hb_join():
            # joined BEFORE release (atexit is LIFO): a heartbeat caught
            # mid-refresh must not resurrect the lock after the unlink
            hb_stop.set()
            hb_thread.join(timeout=5.0)
        atexit.register(_hb_join)
    from kubernetes_tpu.kubemark.benchmark import run_scheduling_benchmark

    # best of two: the box shows ±20% run-to-run noise (shared-host
    # scheduling), and a live scheduler's steady state is the warmer
    # run; both raw numbers ride the artifact
    runs = [run_scheduling_benchmark(args.nodes, args.pods, "batch")
            for _ in range(2)]
    r = max(runs, key=lambda x: x.pods_per_sec)
    if args.verbose:
        print(f"# e2e {r.scheduled}/{r.n_pods} in {r.elapsed_s:.2f}s",
              file=sys.stderr)
    store_ab = None
    if args.store_ab:
        # control arm: same shape, fan-out back under the ledger lock —
        # the measured delta IS the two-phase commit split
        ctl = run_scheduling_benchmark(args.nodes, args.pods, "batch",
                                       store_publish_inline=True)
        store_ab = {
            "publish_offlock_pods_per_sec": round(r.pods_per_sec, 1),
            "publish_inline_pods_per_sec": round(ctl.pods_per_sec, 1),
            "publish_inline_elapsed_s": round(ctl.elapsed_s, 2),
            "speedup": (round(r.pods_per_sec / ctl.pods_per_sec, 3)
                        if ctl.pods_per_sec else None)}
        if args.verbose:
            print(f"# store A/B inline {ctl.pods_per_sec:.0f} vs "
                  f"off-lock {r.pods_per_sec:.0f} pods/s",
                  file=sys.stderr)
    pipeline = None
    if args.txn_ab:
        # control arm: same shape, multi-key txns off — registry batch
        # verbs fall back to per-1024-op store.batch() chunks and the
        # fleet's status pump re-caps its drain at 1024; the measured
        # delta IS the single-revision-window commit + scan/commit
        # overlap (ISSUE 12)
        tc = run_scheduling_benchmark(args.nodes, args.pods, "batch",
                                      txn_commit=False)
        pipeline = {
            "txn_pods_per_sec": round(r.pods_per_sec, 1),
            "txn_elapsed_s": round(r.elapsed_s, 2),
            "chunked_pods_per_sec": round(tc.pods_per_sec, 1),
            "chunked_elapsed_s": round(tc.elapsed_s, 2),
            "speedup": (round(r.pods_per_sec / tc.pods_per_sec, 3)
                        if tc.pods_per_sec else None)}
        if args.verbose:
            print(f"# txn A/B chunked {tc.pods_per_sec:.0f} vs "
                  f"txn {r.pods_per_sec:.0f} pods/s",
                  file=sys.stderr)
        # native arm (ISSUE 17): the same txn tile served end-to-end by
        # the C++ engine — kv_commit_txn ledger window + publish ring
        # draining on the engine's own thread — A/B'd against the
        # native store with the ring off (events publish inline under
        # the engine mutex, on the committer's thread). The delta IS
        # the off-GIL publish. Skipped without a toolchain.
        from kubernetes_tpu.core.native_store import (NativeStore,
                                                      native_available)
        if native_available():
            from kubernetes_tpu.api.registry import Registry
            nst = NativeStore(native_publish=True)
            nr = run_scheduling_benchmark(args.nodes, args.pods, "batch",
                                          registry=Registry(store=nst))
            nstats = nst.publish_stats()
            ctl = run_scheduling_benchmark(
                args.nodes, args.pods, "batch",
                registry=Registry(store=NativeStore(
                    native_publish=False)))
            pipeline["native_publish_pods_per_sec"] = round(
                nr.pods_per_sec, 1)
            pipeline["native_publish_elapsed_s"] = round(nr.elapsed_s, 2)
            pipeline["native_inline_pods_per_sec"] = round(
                ctl.pods_per_sec, 1)
            pipeline["native_inline_elapsed_s"] = round(ctl.elapsed_s, 2)
            pipeline["native_speedup"] = (
                round(nr.pods_per_sec / ctl.pods_per_sec, 3)
                if ctl.pods_per_sec else None)
            pipeline["native_publish_stats"] = nstats
            if args.verbose:
                print(f"# native A/B inline {ctl.pods_per_sec:.0f} vs "
                      f"ring {nr.pods_per_sec:.0f} pods/s",
                      file=sys.stderr)
    obs_section = None
    if args.trace:
        # the causal-tracing arm (ISSUE 13): a traced pass decomposes
        # the run's wall-clock into the pinned lifecycle stages
        # (create -> queue -> schedule -> device -> bind -> publish ->
        # confirm); coverage is the staged seconds summed over the
        # traced pass's e2e wall (>=90% or the decomposition is lying
        # by omission), overhead is traced vs untraced throughput
        # (<5% or the NOOP fast path regressed)
        from kubernetes_tpu import obs as obspkg
        from kubernetes_tpu.utils.metrics import (OBS_STAGE_SUMMARY,
                                                  MetricsRegistry)
        # best of two per arm, same as the headline runs above — a
        # single-shot A/B can't gate at 5% on a ±20%-noise box
        tron = mreg = None
        n_spans = 0
        for _ in range(2):
            reg = MetricsRegistry()
            obspkg.configure(seed=args.trace_seed, metrics=reg)
            r = run_scheduling_benchmark(args.nodes, args.pods, "batch")
            if tron is None or r.pods_per_sec > tron.pods_per_sec:
                tron, mreg = r, reg
                n_spans = len(obspkg.tracer().spans())
        stages = {}
        staged_sum = 0.0
        for k, st in sorted(mreg.summary_stats(OBS_STAGE_SUMMARY).items()):
            stage = dict(k).get("stage", "?")
            staged_sum += st["sum"]
            stages[stage] = {"count": int(st["count"]),
                             "sum_s": round(st["sum"], 3),
                             "p50_ms": round(st["p50"] * 1e3, 3),
                             "p99_ms": round(st["p99"] * 1e3, 3)}
        coverage = (staged_sum / tron.elapsed_s) if tron.elapsed_s else None
        obspkg.configure(seed=args.trace_seed, enabled=False)
        troff = max((run_scheduling_benchmark(args.nodes, args.pods,
                                              "batch") for _ in range(2)),
                    key=lambda x: x.pods_per_sec)
        obspkg.configure(seed=args.trace_seed)  # back to the default
        overhead = (1.0 - tron.pods_per_sec / troff.pods_per_sec
                    if troff.pods_per_sec else None)
        obs_section = {
            "seed": args.trace_seed,
            "traced_pods_per_sec": round(tron.pods_per_sec, 1),
            "untraced_pods_per_sec": round(troff.pods_per_sec, 1),
            "overhead_frac": (round(overhead, 4)
                              if overhead is not None else None),
            "overhead_ok": (overhead is not None and overhead < 0.05),
            "spans": n_spans,
            "stage_coverage_frac": (round(coverage, 3)
                                    if coverage is not None else None),
            "stage_coverage_ok": (coverage is not None
                                  and coverage >= 0.90),
            "stages": stages}
        if args.verbose:
            print(f"# obs traced {tron.pods_per_sec:.0f} vs untraced "
                  f"{troff.pods_per_sec:.0f} pods/s "
                  f"(overhead {overhead:.2%}, coverage {coverage:.2f}, "
                  f"{n_spans} spans)", file=sys.stderr)
    chaos = None
    if args.chaos_seed is not None:
        # the fault-load arm: same shape, every component client wrapped
        # in the seeded injector — records how much throughput survives
        # a faulty control plane (and that the run converges at all)
        cr = run_scheduling_benchmark(args.nodes, args.pods, "batch",
                                      chaos_seed=args.chaos_seed,
                                      chaos_error_rate=args.chaos_rate)
        chaos = {
            "seed": args.chaos_seed,
            "error_rate": args.chaos_rate,
            "pods_per_sec": round(cr.pods_per_sec, 1),
            "elapsed_s": round(cr.elapsed_s, 2),
            "scheduled": cr.scheduled,
            "vs_fault_free": (round(cr.pods_per_sec / r.pods_per_sec, 3)
                              if r.pods_per_sec else None)}
        if args.verbose:
            print(f"# chaos[seed={args.chaos_seed} "
                  f"rate={args.chaos_rate}] {cr.pods_per_sec:.0f} pods/s "
                  f"({cr.scheduled}/{cr.n_pods})", file=sys.stderr)
    node_chaos = None
    if args.node_kill_fraction > 0:
        # the node-failure arm: full stack (fleet + batch scheduler +
        # RC + NodeController) with a seeded mid-run node kill; the
        # recorded numbers are the recovery story — kill time,
        # convergence time, evictions issued, rebind count — plus the
        # zero-dead-bindings gate the soak test enforces
        from kubernetes_tpu.kubemark.node_chaos import run_node_kill_soak
        nk = run_node_kill_soak(
            n_nodes=1000, replicas=600,
            kill_fraction=args.node_kill_fraction,
            seed=args.node_kill_seed, fault_rate=0.05, timeout=420,
            heartbeat_interval=2.0, monitor_period=0.3,
            monitor_grace_period=6.0, pod_eviction_timeout=0.5)
        node_chaos = {
            "seed": args.node_kill_seed,
            "kill_fraction": args.node_kill_fraction,
            "n_nodes": nk.n_nodes,
            "replicas": nk.replicas,
            "converged": nk.converged,
            "killed": len(nk.killed),
            "kill_at_s": nk.kill_at_s,
            "convergence_s": nk.converge_s,
            "evictions": nk.evictions,
            "rebinds": nk.rebinds,
            "dead_bound_at_quiesce": nk.dead_bound,
            "schedule_replayed": nk.schedule_replayed}
        if args.verbose:
            print(f"# node_chaos[seed={args.node_kill_seed} "
                  f"kill={args.node_kill_fraction}] converged="
                  f"{nk.converged} in {nk.converge_s:.1f}s "
                  f"({nk.evictions} evictions, {nk.rebinds} rebinds)",
                  file=sys.stderr)
    durability = None
    if args.wal_dir is not None or args.crash_seed is not None:
        # the durability/HA arm (ISSUE 7): the WAL fsync-policy A/B +
        # recovery replay, and/or the seeded process-crash soak — the
        # exact invariants tests/test_chaos.py's crash gates enforce,
        # recorded so the artifact carries the numbers (recovery
        # wall-clock, replayed records/s, leader transitions)
        from kubernetes_tpu.kubemark.crash_soak import (run_crash_soak,
                                                        run_wal_bench)
        durability = {}
        if args.wal_dir is not None:
            durability["wal"] = run_wal_bench(n_records=args.wal_records,
                                              wal_dir=args.wal_dir)
            if args.verbose:
                w = durability["wal"]
                print(f"# wal always={w['always']['records_per_sec']}/s "
                      f"batch={w['batch']['records_per_sec']}/s "
                      f"recovery={w['recovery']['wall_s']}s",
                      file=sys.stderr)
        if args.crash_seed is not None:
            cs = run_crash_soak(n_nodes=6, replicas=24,
                                seed=args.crash_seed, fault_rate=0.05,
                                timeout=180)
            durability["crash"] = {
                "seed": args.crash_seed,
                "converged": cs.converged,
                "convergence_s": cs.converge_s,
                "killed": cs.killed,
                "schedule_replayed": cs.schedule_replayed,
                "recovery": cs.recovery,
                "duplicate_bindings": len(cs.duplicate_bindings),
                "term_violations": len(cs.term_violations),
                "terms": cs.terms,
                "counters": cs.counters}
            if args.verbose:
                print(f"# crash[seed={args.crash_seed}] converged="
                      f"{cs.converged} in {cs.converge_s:.1f}s "
                      f"(dupes={len(cs.duplicate_bindings)} "
                      f"term_violations={len(cs.term_violations)})",
                      file=sys.stderr)
    workload = None
    if args.workload_seed is not None:
        # the trace-replay arm (ISSUE 8): the exact invariants
        # tests/test_workload.py's soak gate enforces, recorded so the
        # artifact carries per-phase bind throughput + SLO verdicts
        from kubernetes_tpu.chaos import WorkloadPlan
        from kubernetes_tpu.kubemark.workload_soak import run_workload_soak
        if args.workload_trace == "day":
            wp = WorkloadPlan(seed=args.workload_seed, ticks=48,
                              diurnal_period=48, diurnal_base=120,
                              diurnal_amp=80, burst_min=40,
                              burst_max=120)
            wr = run_workload_soak(
                n_nodes=1000, plan=wp, tick_wall_s=0.5,
                fault_rate=0.05, node_kill_fraction=0.10,
                timeout=900.0, heartbeat_interval=3.0,
                monitor_period=0.5, monitor_grace_period=8.0,
                pod_eviction_timeout=0.5, bind_p99_limit_s=8.0)
        else:
            wp = WorkloadPlan(seed=args.workload_seed, ticks=12)
            wr = run_workload_soak(
                n_nodes=12, plan=wp, tick_wall_s=0.4, fault_rate=0.05,
                node_kill_fraction=0.10, timeout=120.0)
        workload = {"trace": args.workload_trace, **wr.as_dict()}
        workload.pop("hpa_track", None)
        if args.verbose:
            print(f"# workload[seed={args.workload_seed} "
                  f"trace={args.workload_trace}] slo_ok={wr.slo_ok} "
                  f"bind_p99={wr.bind_p99_s}s "
                  f"lag={wr.hpa_max_lag_ticks} ticks "
                  f"phases={[p['binds'] for p in wr.phases]}",
                  file=sys.stderr)
    preemption = None
    if args.flash_drain:
        # the priority/preemption arm (ISSUE 20): the exact invariants
        # tests/test_preemption.py's soak gate enforces — zero wrongful
        # evictions (oracle-audited), zero duplicate bindings, every
        # surge pod bound under the fast-bind limit — recorded so the
        # artifact carries the drain story end to end
        from kubernetes_tpu.kubemark.workload_soak import \
            run_flash_drain_soak
        fd = run_flash_drain_soak(seed=args.flash_drain_seed)
        preemption = fd.as_dict()
        if args.verbose:
            edges = [(a["sample"], a["action"]) for a in fd.alerts
                     if a["slo"] == "surge-bind-availability"]
            print(f"# preemption[seed={args.flash_drain_seed}] "
                  f"surge {fd.surge_bound}/{fd.surge_pods} bound "
                  f"p99={fd.surge_bind_p99_s}s "
                  f"victims={fd.victims_evicted} "
                  f"wrongful={fd.wrongful_evictions} alerts={edges}",
                  file=sys.stderr)
    metricsplane = None
    if args.timeseries:
        # the metrics-plane arm (ISSUE 14): one fast trace replay with
        # the scraper + burn-rate evaluator on — the artifact carries
        # the full sorted-key series export (what tools/obs_report.py
        # renders) and the alert timeline, gated the same way the soak
        # test gates (crowd fast-burn must trip AND clear)
        from kubernetes_tpu.chaos import WorkloadPlan as _WP
        from kubernetes_tpu.kubemark.workload_soak import run_workload_soak
        mp_seed = args.workload_seed if args.workload_seed is not None \
            else 2
        mw = run_workload_soak(
            n_nodes=12, plan=_WP(seed=mp_seed, ticks=12),
            tick_wall_s=0.4, fault_rate=0.05, node_kill_fraction=0.10,
            timeout=120.0, scrape=True, keep_series=True)
        crowd_trips = [a for a in mw.alerts
                       if a["action"] == "TRIP"
                       and a["slo"] == "crowd-bind-availability"]
        # scrape-overhead control: best-of-two e2e passes with a
        # FleetScraper polling the fleet registry flat-out vs the
        # headline (unscraped) best — same gate shape as the --trace
        # arm's overhead (<5% or render()/observe() regressed)
        from kubernetes_tpu.obs.metricsplane import (FleetScraper,
                                                     RegistryTarget)
        from kubernetes_tpu.utils.metrics import global_metrics
        sc = FleetScraper([RegistryTarget("fleet", global_metrics)],
                          cadence_s=0.05)
        sc.start()
        try:
            scraped = max(
                (run_scheduling_benchmark(args.nodes, args.pods,
                                          "batch") for _ in range(2)),
                key=lambda x: x.pods_per_sec)
        finally:
            sc.stop()
        base = max(runs, key=lambda x: x.pods_per_sec)
        sc_overhead = (1.0 - scraped.pods_per_sec / base.pods_per_sec
                       if base.pods_per_sec else None)
        metricsplane = {
            "seed": mp_seed,
            "samples": mw.scrape_samples,
            "counter_resets": mw.scrape_resets,
            "scrape_errors": mw.scrape_errors,
            "alerts": mw.alerts,
            "alerts_ok": mw.alerts_ok,
            "fast_burn_tripped": bool(crowd_trips),
            "slo_ok": mw.slo_ok,
            "series": mw.scrape_export,
            "scraped_pods_per_sec": round(scraped.pods_per_sec, 1),
            "unscraped_pods_per_sec": round(base.pods_per_sec, 1),
            "overhead_frac": (round(sc_overhead, 4)
                              if sc_overhead is not None else None),
            "overhead_ok": (sc_overhead is not None
                            and sc_overhead < 0.05)}
        if args.verbose:
            edges = [(a["sample"], a["action"]) for a in mw.alerts]
            print(f"# metricsplane[seed={mp_seed}] "
                  f"samples={mw.scrape_samples} alerts={edges} "
                  f"scraped {scraped.pods_per_sec:.0f} vs "
                  f"{base.pods_per_sec:.0f} pods/s",
                  file=sys.stderr)
    serving = None
    if args.watch_fanout:
        # the serving-plane arm (ISSUE 18): the fan-out storm against
        # the N-worker pool — the recorded numbers are the delivery
        # story (create-storm throughput, per-worker lag percentiles,
        # the watch-deliver burn-rate verdict) plus the 1-vs-N scaling
        # readout; on a 1-core box the wall-clock ratio can't show
        # scaling, so the multi-consumer overlap witness gates and the
        # caveat rides the artifact instead of a flattering number
        from kubernetes_tpu.kubemark.fanout_soak import run_fanout_soak
        fr = run_fanout_soak(n_watchers=args.watch_fanout,
                             workers=args.fanout_workers)
        serving = fr.as_dict()
        from kubernetes_tpu.kubemark.tpu_evidence import _atomic_write_json
        here = os.path.dirname(os.path.abspath(__file__))
        _atomic_write_json(
            os.path.join(here, "SLO_10KWATCH.json"),
            {"metric": "watch_fanout_slo",
             "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
             "n_watchers": fr.n_watchers, "workers": fr.workers,
             "slo": "watch-deliver-250ms",
             "watch_slo_ok": fr.arm.watch_slo_ok,
             "lag_p50_ms": fr.arm.lag_p50_ms,
             "lag_p99_ms": fr.arm.lag_p99_ms,
             "alerts": fr.arm.alerts,
             "per_worker": fr.arm.per_worker,
             "overlap": fr.arm.overlap,
             "scaling": {"ratio": fr.scaling_ratio,
                         "gate": fr.scaling_gate,
                         "ok": fr.scaling_ok,
                         "caveat": fr.caveat}})
        if args.verbose:
            print(f"# serving[{fr.n_watchers} watchers x "
                  f"{fr.workers} workers] ok={fr.ok} "
                  f"p99={fr.arm.lag_p99_ms}ms "
                  f"scaling={fr.scaling_ratio}x via {fr.scaling_gate}",
                  file=sys.stderr)
    engine_rate, engine_bound = engine_only(args.nodes, args.pods)
    multichip = None
    if args.mesh_devices:
        multichip = _multichip_section(args)
        if args.verbose:
            effs = [(g["n_devices"], g.get("scaling_efficiency"))
                    for g in multichip["ladder"]]
            print(f"# multichip parity_ok={multichip['parity_ok']} "
                  f"efficiency={effs}", file=sys.stderr)
    pallas = _pallas_status(platform)

    import jax
    if (platform == "default" and jax.default_backend() == "tpu"
            and (args.nodes, args.pods) == (5000, 30000)):
        # the headline run IS a real-TPU measurement at the evidence
        # suite's north-star shape — fold it into the per-section BEST
        # artifact so the demonstrated ceiling reflects every on-chip
        # run, not only the watcher's captures. Gated on the REAL
        # backend, not probe success: a cpu-default box also reports
        # platform "default" and must never masquerade as chip evidence
        from kubernetes_tpu.kubemark.tpu_evidence import merge_best
        here = os.path.dirname(os.path.abspath(__file__))
        merge_best(
            {"sections": {
                 "e2e": {"status": "ok",
                         "pods_per_sec": round(r.pods_per_sec, 1),
                         "elapsed_s": round(r.elapsed_s, 2),
                         "runs_pods_per_sec": [round(x.pods_per_sec, 1)
                                               for x in runs],
                         "scheduled": r.scheduled, "nodes": r.n_nodes,
                         "pods": r.n_pods, "source": "bench"},
                 "engine": {"status": "ok",
                            "5000x30000": {
                                "pods_per_sec": round(engine_rate, 1),
                                "bound": engine_bound,
                                "source": "bench"}}}},
            os.path.join(here, "TPU_EVIDENCE_BEST.json"))

    slo = None
    if not args.skip_slo:
        # the reference's density matrix at two points (3 and 30
        # pods/node, test/e2e/density.go:203-208), 1000 nodes each;
        # latency percentiles are server-side (see kubemark/slo.py)
        from kubernetes_tpu.kubemark.slo import (MIN_API_SAMPLES,
                                                 run_density_slo)
        points = []
        for ppn in (3, 30):
            s = run_density_slo(n_nodes=1000, n_pods=1000 * ppn)
            points.append(s.as_dict())
            if args.verbose:
                print(f"# slo[{ppn}/node] api_p99="
                      f"{points[-1]['api_p99_ms']}ms "
                      f"calls={points[-1]['api_calls']} "
                      f"startup_p50={points[-1]['startup_p50_s']}s",
                      file=sys.stderr)
        total_calls = sum(p["api_calls"] for p in points)
        # null-coupled gate: a starved point reports api_slo_ok null
        # (kubemark/slo.py api_ok) and poisons the matrix verdict to
        # null — never true-on-starved-samples
        per_point = [p["api_slo_ok"] for p in points]
        slo = {
            "density_points": points,
            "api_calls": total_calls,
            "api_slo_ok": (None if any(v is None for v in per_point)
                           else all(per_point)),
            "startup_slo_ok": all(p["startup_slo_ok"] for p in points),
            # the matrix-wide floor: the 3/node point's window is only
            # a few seconds (per-point validity stays reported above)
            "api_samples_valid": total_calls >= MIN_API_SAMPLES}

    # regenerate the multi-host DCN-path proof every round (4 procs x 2
    # virtual CPU devices, bindings asserted bit-equal across
    # processes) — a standing artifact, not a one-time capture.
    # --fail-shard adds the shard-failure gate: wedged-worker detection
    # + survivor-shape relaunch parity + the in-process shard-kill
    # soak's lease/epoch/replay verdicts (ISSUE 19)
    multihost = None
    repo = os.path.dirname(os.path.abspath(__file__))
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(repo, "tools",
                                          "dryrun_multihost.py"),
             "--procs", "4", "--fail-shard", "--out",
             os.path.join(repo, "MULTIHOST.json")],
            capture_output=True, text=True, timeout=900, cwd=repo)
        for line in reversed(proc.stdout.splitlines()):
            line = line.strip()
            if line.startswith("{"):
                multihost = json.loads(line)
                break
        if multihost is None:
            multihost = {"multihost_dryrun_ok": False,
                         "error": proc.stderr[-500:]}
    except Exception as e:
        multihost = {"multihost_dryrun_ok": False, "error": str(e)[:500]}
    if not multihost.get("multihost_dryrun_ok"):
        # a failed round must not leave the previous round's ok:true
        # artifact on disk (same contract as the soak artifact)
        from kubernetes_tpu.kubemark.tpu_evidence import _atomic_write_json
        _atomic_write_json(os.path.join(repo, "MULTIHOST.json"), multihost)

    print(json.dumps({
        "metric": "e2e_scheduling_throughput_5k_nodes",
        "value": round(r.pods_per_sec, 1),
        "unit": "pods/sec",
        "vs_baseline": round(r.pods_per_sec / 50.0, 1),
        "e2e_elapsed_s": round(r.elapsed_s, 2),
        "e2e_runs": [round(x.pods_per_sec, 1) for x in runs],
        "scheduled": r.scheduled,
        "nodes": r.n_nodes,
        "pods": r.n_pods,
        "engine_only_pods_per_sec": round(engine_rate, 1),
        "platform": platform,
        "probe": probe,
        "pallas": pallas,
        "slo": slo,
        "store_ab": store_ab,
        "pipeline": pipeline,
        "obs": obs_section,
        "chaos": chaos,
        "node_chaos": node_chaos,
        "durability": durability,
        "workload": workload,
        "metricsplane": metricsplane,
        "preemption": preemption,
        "serving": serving,
        "multichip": multichip,
        "multihost": multihost,
        "lint": lint_section,
        "tpu": _tpu_section()}))


if __name__ == "__main__":
    main()
